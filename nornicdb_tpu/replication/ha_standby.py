"""HA standby replication: WAL streaming, heartbeats, fencing, failover.

Reference: pkg/replication/ha_standby.go:170-779 — the primary streams
WAL batches to standbys and heartbeats; standbys monitor primary health
and auto-fail over (with fencing epochs so a deposed primary's writes
are rejected). Handlers (HandleWALBatch/HandleHeartbeat/HandleFence,
ha_standby.go:736-779) are directly callable so multi-replica tests run
in one process without real sockets (SURVEY.md §4 "multi-node without a
real cluster").

Epoch rules:
- every message carries the sender's epoch;
- a receiver rejects messages with epoch < its own (fenced);
- failover: the standby increments epoch, promotes, and best-effort
  fences the old primary, which steps down on seeing the higher epoch.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from nornicdb_tpu.replication.replicator import (
    NotPrimaryError,
    ReplicationConfig,
    Replicator,
    Role,
    decode_op_args,
)
from nornicdb_tpu.replication.transport import ClusterMessage, ClusterTransport
from nornicdb_tpu.storage.wal_engine import WALEngine


class HAPrimary(Replicator):
    """Primary: applies writes locally (through the WALEngine so order
    and durability hold), then streams them to standbys — synchronously
    for quorum mode, from a background thread for async mode."""

    def __init__(
        self,
        engine: WALEngine,
        transport: ClusterTransport,
        config: ReplicationConfig,
    ):
        self.engine = engine
        self.transport = transport
        self.config = config
        self.epoch = 1
        self._role = Role.PRIMARY
        self._lock = threading.Lock()
        self._pending: List[Dict[str, Any]] = []
        self._pending_cv = threading.Condition(self._lock)
        self._closed = threading.Event()
        self._threads: List[threading.Thread] = []
        transport.register_handler("fence", self.handle_fence)
        transport.register_handler("wal_sync", self.handle_wal_sync)

    def start(self) -> None:
        if self.config.sync == "async":
            t = threading.Thread(target=self._stream_loop, daemon=True,
                                 name="ha-stream")
            t.start()
            self._threads.append(t)
        hb = threading.Thread(target=self._heartbeat_loop, daemon=True,
                              name="ha-heartbeat")
        hb.start()
        self._threads.append(hb)

    # -- replicator ------------------------------------------------------

    def apply(self, op: str, data: Dict[str, Any]) -> None:
        with self._lock:
            if self._role is not Role.PRIMARY:
                raise NotPrimaryError()
            epoch = self.epoch
        # local first: WALEngine sequences + persists it. The record's seq is
        # captured atomically under the WALEngine mutation lock (apply_op
        # returns it), and for async mode the pending enqueue happens inside
        # that same lock via on_logged — so stream order always matches seq
        # order even with concurrent appliers (a post-hoc read of
        # wal.last_seq could tag two interleaved writes with the same seq
        # and the standby would silently drop one). ``ts`` is the primary
        # append instant: replicas difference it against their apply time
        # into nornicdb_replication_apply_delay_seconds (ISSUE 13).
        rec: Dict[str, Any] = {"op": op, "data": data,
                               "ts": round(time.time(), 6)}

        if self.config.sync == "quorum":
            rec["seq"] = self.engine.apply_op(op, data)
            self._replicate_quorum([rec], epoch)
        else:
            def enqueue(seq: int) -> None:
                rec["seq"] = seq
                with self._pending_cv:
                    self._pending.append(rec)
                    self._pending_cv.notify()

            self.engine.apply_op(op, data, on_logged=enqueue)

    @property
    def role(self) -> Role:
        with self._lock:
            return self._role

    # -- streaming -------------------------------------------------------

    def _batch_msg(self, records: List[Dict[str, Any]], epoch: int) -> ClusterMessage:
        return {
            "type": "wal_batch",
            "epoch": epoch,
            "records": records,
            "primary": self.config.node_id,
        }

    def _replicate_quorum(self, records: List[Dict[str, Any]], epoch: int) -> None:
        """Quorum sync (reference: sync mode quorum, config.go:133-142):
        the write acks only once a majority of the cluster (primary
        included) has it."""
        msg = self._batch_msg(records, epoch)
        max_seq = max((r.get("seq", 0) for r in records), default=0)
        replies = self.transport.broadcast(self.config.peers, msg)
        # an ack only counts if the standby has APPLIED through this
        # batch's last seq (a buffered-but-unapplied batch must not reach
        # quorum — those records are lost if the primary dies now)
        acks = 1 + sum(
            1
            for r in replies.values()
            if r is not None
            and r.get("ok")
            and r.get("applied_seq", 0) >= max_seq
        )
        need = (len(self.config.peers) + 1) // 2 + 1
        if acks < need:
            raise ConnectionError(
                f"quorum not reached: {acks}/{need} acks"
            )

    def _stream_loop(self) -> None:
        while not self._closed.is_set():
            with self._pending_cv:
                while not self._pending and not self._closed.is_set():
                    self._pending_cv.wait(timeout=0.2)
                batch, self._pending = self._pending, []
                epoch = self.epoch
            if batch:
                self.transport.broadcast(
                    self.config.peers, self._batch_msg(batch, epoch)
                )

    def _heartbeat_loop(self) -> None:
        while not self._closed.is_set():
            with self._lock:
                if self._role is not Role.PRIMARY:
                    return
                epoch = self.epoch
            self.transport.broadcast(
                self.config.peers,
                {
                    "type": "heartbeat",
                    "epoch": epoch,
                    "primary": self.config.node_id,
                    "last_seq": self.engine.wal.last_seq,
                },
                timeout=self.config.heartbeat_interval,
            )
            self._closed.wait(self.config.heartbeat_interval)

    # -- handlers --------------------------------------------------------

    def handle_fence(self, msg: ClusterMessage) -> ClusterMessage:
        """A higher epoch deposes this primary (reference: fencing,
        ha_standby.go HandleFence :779)."""
        with self._lock:
            if msg.get("epoch", 0) > self.epoch:
                self._role = Role.STANDBY
                self.epoch = msg["epoch"]
                return {"ok": True, "stepped_down": True}
        return {"ok": False, "error": "stale fence epoch"}

    def handle_wal_sync(self, msg: ClusterMessage) -> ClusterMessage:
        """Catch-up: a (re)joining standby asks for records after seq N.
        Records ship seq-tagged and in log order so the standby can apply
        them strictly in order and advance its watermark precisely.

        If auto-compaction has pruned the segments covering the
        requested range (the standby is behind the newest snapshot's
        seq), the reply ALSO carries that snapshot: WAL records alone
        could only rebuild the post-snapshot tail, so a fresh replica
        joining a long-lived primary would silently open near-empty.
        The standby applies the snapshot state first (idempotent
        creates — meant for empty/near-empty joiners; a diverged
        rejoiner should start from a fresh data dir), pins its
        watermark at the snapshot seq, then replays the tail."""
        from_seq = int(msg.get("from_seq", 0))
        # drain buffered appends to the segment files, then read from them
        self.engine.wal.flush()
        snapshot = None
        snapshot_seq = 0
        try:
            if from_seq < self.engine.wal.earliest_retained_seq():
                # records alone cannot rebuild the requested range —
                # pruned history must ship as the snapshot. A standby
                # INSIDE the retention window never takes this branch:
                # it catches up from the retained records exactly as
                # before (the snapshot reconcile is strictly for
                # behind-the-horizon joiners).
                state, snap_seq = self.engine.wal.load_snapshot()
                if state is not None and snap_seq > from_seq:
                    snapshot, snapshot_seq = state, snap_seq
                    from_seq = snap_seq
        except Exception:  # noqa: BLE001 — unreadable snapshot: records-only
            pass
        records = [
            {"seq": rec.get("seq", 0), "op": rec["op"],
             "data": rec.get("data", {}), "ts": rec.get("ts", 0)}
            for rec in self.engine.wal.iter_records(from_seq=from_seq)
        ]
        last_seq = records[-1]["seq"] if records else from_seq
        with self._lock:
            epoch = self.epoch
        reply: ClusterMessage = {
            "ok": True,
            "epoch": epoch,
            "records": records,
            "last_seq": last_seq,
        }
        if snapshot is not None:
            reply["snapshot"] = snapshot
            reply["snapshot_seq"] = snapshot_seq
        return reply

    def close(self) -> None:
        """Drain any pending async batch synchronously before shutdown so
        locally-acked writes reach the standbys even on a fast exit."""
        with self._pending_cv:
            tail, self._pending = self._pending, []
            epoch = self.epoch
            self._closed.set()
            self._pending_cv.notify_all()
        if tail:
            try:
                self.transport.broadcast(
                    self.config.peers, self._batch_msg(tail, epoch),
                    timeout=2.0,
                )
            except ConnectionError:
                pass


class HAStandby(Replicator):
    """Standby: applies streamed WAL batches, monitors primary health,
    and auto-promotes (with fencing) when the primary goes silent
    (reference: ha_standby.go:350-502 health monitor + failover)."""

    def __init__(
        self,
        engine: WALEngine,
        transport: ClusterTransport,
        config: ReplicationConfig,
        primary_addr: Optional[Tuple[str, int]] = None,
        on_promote: Optional[Callable[["HAStandby"], None]] = None,
    ):
        self.engine = engine
        self.transport = transport
        self.config = config
        self.primary_addr = primary_addr
        self.on_promote = on_promote
        # fencing epoch: persisted across restarts when config.epoch_path
        # is set (ISSUE 16). A replica that restarts at epoch 1 after a
        # failover bumped the fleet to epoch 2 would accept the deposed
        # primary's stream — loading the persisted epoch closes that
        # window, and together with the seq-aligned local WAL lets the
        # restarted replica resume without a full re-bootstrap.
        self.epoch = self._load_epoch()
        # first boot writes the initial epoch too: the file's existence
        # is the restart contract (resume_epoch in the fleet ready doc)
        if getattr(config, "epoch_path", None) \
                and not os.path.exists(config.epoch_path):
            self._persist_epoch(self.epoch)
        self.applied_seq = 0
        # records received ahead of the watermark, held until the gap fills
        # (strict in-order apply: an older write applied after a newer one
        # to the same key would silently diverge the replica)
        self._reorder_buf: Dict[int, Dict[str, Any]] = {}
        self._sync_lock = threading.Lock()  # one catch-up at a time
        self._role = Role.STANDBY
        self._lock = threading.Lock()
        self._last_heartbeat = time.monotonic()
        self._closed = threading.Event()
        self._as_primary: Optional[HAPrimary] = None  # set on promote
        transport.register_handler("wal_batch", self.handle_wal_batch)
        transport.register_handler("heartbeat", self.handle_heartbeat)
        transport.register_handler("fence", self.handle_fence)

    def start(self, monitor: bool = True) -> None:
        if monitor:
            with self._lock:
                # the silence clock starts NOW, not at construction: a
                # slow open between __init__ and start (embedder/model
                # loading in the DB facade) must not count as primary
                # silence — a standby that promotes itself because its
                # own boot was slow is split-brain at startup
                self._last_heartbeat = time.monotonic()
            t = threading.Thread(target=self._monitor_loop, daemon=True,
                                 name="ha-monitor")
            t.start()

    # -- epoch persistence (ISSUE 16) ------------------------------------

    def _load_epoch(self) -> int:
        path = getattr(self.config, "epoch_path", None)
        if not path:
            return 1
        try:
            with open(path, "r", encoding="utf-8") as f:
                return max(1, int(f.read().strip() or 1))
        except (OSError, ValueError):
            return 1

    def _persist_epoch(self, epoch: int) -> None:
        """Atomic (tmp+rename) epoch write — a torn file read back as
        garbage would reset a restarted replica to epoch 1."""
        path = getattr(self.config, "epoch_path", None)
        if not path:
            return
        tmp = f"{path}.tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                f.write(str(epoch))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except OSError:
            pass  # persistence is best-effort; the live epoch still holds

    def _set_epoch_locked(self, epoch: int) -> None:
        """Single choke point for epoch advances (caller holds _lock):
        updates the live value and rewrites the persisted copy only on
        actual change."""
        if epoch > self.epoch:
            self.epoch = epoch
            self._persist_epoch(epoch)

    # -- replicator ------------------------------------------------------

    def apply(self, op: str, data: Dict[str, Any]) -> None:
        with self._lock:
            if self._role is not Role.PRIMARY:
                raise NotPrimaryError()
            primary = self._as_primary
        if primary is not None:
            # post-failover: full primary behavior (stream + heartbeat)
            primary.apply(op, data)
        else:
            getattr(self.engine, op)(*decode_op_args(op, data))

    def _apply_record(self, op: str, data: Dict[str, Any],
                      seq: int = 0, ts: float = 0.0) -> None:
        """One streamed/caught-up record -> the engine. ``seq`` is the
        PRIMARY's sequence number for the record (0 = unsequenced),
        ``ts`` the primary's append timestamp (0 = unknown — a record
        from an older primary). Indirection so subclasses can change
        apply semantics fleet-wide: read replicas apply AND log under
        the primary's seq — WALEngine.apply_and_log(seq=...) — keeping
        their local WAL seq-aligned for promotion/rejoin even when
        they joined mid-history, and observe the append->apply delay
        into nornicdb_replication_apply_delay_seconds (ISSUE 13)."""
        self.engine.apply_record(op, data)

    @property
    def role(self) -> Role:
        with self._lock:
            return self._role

    # -- handlers (directly callable in tests) ---------------------------

    def handle_wal_batch(self, msg: ClusterMessage) -> ClusterMessage:
        with self._lock:
            if msg.get("epoch", 0) < self.epoch:
                return {"ok": False, "error": "fenced: stale epoch"}
            self._set_epoch_locked(msg.get("epoch", 0))
            self._last_heartbeat = time.monotonic()
        # Strict in-order apply. quorum mode broadcasts each record
        # independently, so batches from concurrent writers can arrive
        # reordered; applying on arrival would let an older write land
        # after a newer one to the same key (silent divergence), and a
        # create/update inversion loses the update entirely (apply_record
        # swallows the not-found). Out-of-order records are buffered and a
        # catch-up from the primary fills the gap.
        need_repair = False
        max_seq = 0
        for rec in sorted(msg.get("records", []), key=lambda r: r.get("seq", 0)):
            seq = rec.get("seq", 0)
            max_seq = max(max_seq, seq)
            with self._lock:
                if seq <= 0:
                    self._apply_record(rec["op"], rec["data"],
                                       ts=rec.get("ts", 0.0))
                    continue
                if seq <= self.applied_seq or seq in self._reorder_buf:
                    continue  # duplicate batch overlap
                if seq == self.applied_seq + 1:
                    self._apply_record(rec["op"], rec["data"], seq=seq,
                                       ts=rec.get("ts", 0.0))
                    self.applied_seq = seq
                    self._drain_reorder_buf_locked()
                else:
                    self._reorder_buf[seq] = rec
                    need_repair = True
        if need_repair:
            # a gap precedes the buffered records: pull the missing range
            # from the primary (fresh standby joining an established
            # primary hits this on its first batch and pulls full history)
            self.catch_up()
        with self._lock:
            # ok means APPLIED, not received: a quorum primary counts this
            # ack toward durability, so a batch that is only buffered
            # (gap repair failed) must not be acknowledged
            return {
                "ok": self.applied_seq >= max_seq,
                "applied_seq": self.applied_seq,
            }

    def _drain_reorder_buf_locked(self) -> None:
        while self.applied_seq + 1 in self._reorder_buf:
            nxt = self._reorder_buf.pop(self.applied_seq + 1)
            self._apply_record(nxt["op"], nxt["data"],
                               seq=self.applied_seq + 1,
                               ts=nxt.get("ts", 0.0))
            self.applied_seq += 1

    def handle_heartbeat(self, msg: ClusterMessage) -> ClusterMessage:
        with self._lock:
            if msg.get("epoch", 0) < self.epoch:
                return {"ok": False, "error": "fenced: stale epoch"}
            self._set_epoch_locked(msg.get("epoch", 0))
            self._last_heartbeat = time.monotonic()
            return {"ok": True, "applied_seq": self.applied_seq}

    def handle_fence(self, msg: ClusterMessage) -> ClusterMessage:
        with self._lock:
            if msg.get("epoch", 0) > self.epoch:
                self._set_epoch_locked(msg["epoch"])
                self._role = Role.STANDBY
                return {"ok": True}
        return {"ok": False, "error": "stale fence epoch"}

    # -- failover --------------------------------------------------------

    def _monitor_loop(self) -> None:
        while not self._closed.is_set():
            self._closed.wait(self.config.heartbeat_interval)
            with self._lock:
                if self._role is not Role.STANDBY:
                    return
                silent = time.monotonic() - self._last_heartbeat
            if silent > self.config.failover_timeout:
                self.promote()
                return

    def promote(self) -> None:
        """Take over as primary: bump epoch, fence the old primary
        (best-effort), flip role, and stand up full primary behavior —
        WAL streaming to the remaining replicas, heartbeats, and the
        wal_sync catch-up handler for a rejoining old primary
        (reference: auto-failover with fencing, ha_standby.go:350-502)."""
        with self._lock:
            if self._role is Role.PRIMARY:
                return
            self._set_epoch_locked(self.epoch + 1)
            self._role = Role.PRIMARY
            epoch = self.epoch
        # replicate onward to the other replicas; the deposed primary's
        # address joins the peer set so it receives the stream when it
        # rejoins as a standby
        peers = [tuple(p) for p in self.config.peers]
        if self.primary_addr is not None and tuple(self.primary_addr) not in peers:
            peers.append(tuple(self.primary_addr))
        cfg = ReplicationConfig(
            mode="ha_standby",
            sync=self.config.sync,
            node_id=self.config.node_id,
            peers=peers,
            heartbeat_interval=self.config.heartbeat_interval,
            failover_timeout=self.config.failover_timeout,
            ha_role="primary",
        )
        primary = HAPrimary(self.engine, self.transport, cfg)
        primary.epoch = epoch
        primary.start()
        with self._lock:
            self._as_primary = primary

        # HAPrimary registered its own fence handler on the shared
        # transport; wrap it so a higher-epoch fence also demotes THIS
        # object (otherwise the outer role stays PRIMARY: local split
        # brain)
        def _fence_after_promote(msg):
            r = primary.handle_fence(msg)
            if r.get("stepped_down"):
                with self._lock:
                    self._role = Role.STANDBY
                    self._set_epoch_locked(primary.epoch)
                    self._as_primary = None
            return r

        self.transport.register_handler("fence", _fence_after_promote)
        if self.primary_addr is not None:
            try:
                self.transport.request(
                    self.primary_addr,
                    {"type": "fence", "epoch": epoch},
                    timeout=1.0,
                )
            except ConnectionError:
                pass  # old primary is gone — that's why we're here
        if self.on_promote is not None:
            self.on_promote(self)

    def _apply_snapshot(self, state: Dict[str, Any], snap_seq: int) -> int:
        """Reconcile against the state shipped by ``handle_wal_sync``
        when the requested range predates the primary's retention
        horizon. The snapshot is the primary's FULL state at
        ``snap_seq``, so it applies authoritatively: present entries
        UPSERT (a stale local copy is overwritten, never kept) and
        local entries ABSENT from the snapshot are deleted (a deletion
        that happened inside the pruned range must not resurrect).
        Entries bypass the local WAL — their primary seqs are unknown,
        and logging them under invented numbers would collide with the
        primary's real seq space (subclasses persist differently:
        FleetStandby pins the counter and writes a local snapshot).
        Caller holds the lock. Returns entries touched."""
        n = 0
        node_ids = set()
        edge_ids = set()
        for nd in state.get("nodes", []) or []:
            nid = str(nd.get("id", ""))
            node_ids.add(nid)
            op = ("update_node" if self.engine.has_node(nid)
                  else "create_node")
            self.engine.apply_record(op, nd)
            n += 1
        for ed in state.get("edges", []) or []:
            eid = str(ed.get("id", ""))
            edge_ids.add(eid)
            op = ("update_edge" if self.engine.has_edge(eid)
                  else "create_edge")
            self.engine.apply_record(op, ed)
            n += 1
        # drop local state the snapshot does not carry — edges first so
        # node-delete cascades never race this scan
        for edge in list(self.engine.all_edges()):
            if edge.id not in edge_ids:
                self.engine.apply_record("delete_edge", {"id": edge.id})
                n += 1
        for node in list(self.engine.all_nodes()):
            if node.id not in node_ids:
                self.engine.apply_record("delete_node", {"id": node.id})
                n += 1
        return n

    def catch_up(self, addr: Optional[Tuple[str, int]] = None) -> int:
        """Pull missed records from the primary (rejoin path, and gap
        repair when a streamed batch arrives ahead of the watermark).
        Returns number of records applied."""
        target = addr or self.primary_addr
        if target is None:
            return 0
        with self._sync_lock:
            with self._lock:
                from_seq = self.applied_seq
            try:
                resp = self.transport.request(
                    target, {"type": "wal_sync", "from_seq": from_seq}
                )
            except ConnectionError:
                return 0
            if not resp.get("ok"):
                return 0
            n = 0
            with self._lock:
                snap = resp.get("snapshot")
                snap_seq = int(resp.get("snapshot_seq", 0) or 0)
                if snap is not None and snap_seq > self.applied_seq:
                    n += self._apply_snapshot(snap, snap_seq)
                    self.applied_seq = max(self.applied_seq, snap_seq)
                for rec in resp.get("records", []):
                    seq = rec.get("seq", 0)
                    if 0 < seq <= self.applied_seq:
                        continue
                    self._apply_record(rec["op"], rec["data"],
                                       seq=max(seq, 0),
                                       ts=rec.get("ts", 0.0))
                    n += 1
                    if seq > 0:
                        self.applied_seq = max(self.applied_seq, seq)
                self.applied_seq = max(
                    self.applied_seq, resp.get("last_seq", 0)
                )
                self._reorder_buf = {
                    s: r for s, r in self._reorder_buf.items()
                    if s > self.applied_seq
                }
                self._drain_reorder_buf_locked()
            return n

    def close(self) -> None:
        self._closed.set()
        with self._lock:
            primary = self._as_primary
        if primary is not None:
            primary.close()


# shared decode lives in replicator.py; kept as a module alias because
# tests and callers address it from here too
_op_args = decode_op_args
