"""Raft consensus: leader election + log replication over the cluster mesh.

Reference: pkg/replication/raft.go:14-60 (Raft mode) — terms, randomized
election timeouts, RequestVote / AppendEntries, majority commit, state
machine apply. The state machine here is a storage engine: committed
entries are {op, data} mutations applied through the same vocabulary as
WAL records, so a Raft cluster and an HA pair converge via identical
replay code.

Single-process multi-node testing: construct N RaftNodes sharing loopback
transports (or call handlers directly), as the reference's replication
tests do (replication_test.go, scenario_test.go).
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from nornicdb_tpu.replication.replicator import (
    NotPrimaryError,
    ReplicationConfig,
    Replicator,
    Role,
)
from nornicdb_tpu.replication.transport import ClusterMessage, ClusterTransport


class RaftNode(Replicator):
    """One Raft participant. States: follower (STANDBY), candidate,
    leader (PRIMARY)."""

    def __init__(
        self,
        transport: ClusterTransport,
        config: ReplicationConfig,
        apply_fn: Callable[[str, Dict[str, Any]], None],
    ):
        self.transport = transport
        self.config = config
        self.apply_fn = apply_fn

        self.term = 0
        self.voted_for: Optional[str] = None
        self.log: List[Dict[str, Any]] = []  # {term, op, data}
        self.commit_index = 0  # 1-based count of committed entries
        self.last_applied = 0
        self.leader_id: Optional[str] = None

        self._state = Role.STANDBY
        self._lock = threading.Lock()
        self._commit_cv = threading.Condition(self._lock)
        self._last_leader_contact = time.monotonic()
        self._closed = threading.Event()
        # leader bookkeeping: next log index to send each peer (1-based)
        self._next_index: Dict[Tuple[str, int], int] = {}

        transport.register_handler("request_vote", self.handle_request_vote)
        transport.register_handler("append_entries", self.handle_append_entries)

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        threading.Thread(
            target=self._election_loop, daemon=True,
            name=f"raft-elect-{self.config.node_id}",
        ).start()

    def close(self) -> None:
        self._closed.set()
        with self._commit_cv:
            self._commit_cv.notify_all()

    # -- replicator ------------------------------------------------------

    @property
    def role(self) -> Role:
        with self._lock:
            return self._state

    def apply(self, op: str, data: Dict[str, Any]) -> None:
        """Append to the leader's log, replicate, wait for majority
        commit, then apply. Raises NotPrimaryError on followers."""
        with self._lock:
            if self._state is not Role.PRIMARY:
                raise NotPrimaryError(self.leader_id)
            entry = {"term": self.term, "op": op, "data": data}
            self.log.append(entry)
            target = len(self.log)
        self._replicate_once()
        deadline = time.monotonic() + 5.0
        with self._commit_cv:
            while self.commit_index < target:
                if self._state is not Role.PRIMARY:
                    raise NotPrimaryError(self.leader_id)
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError("raft commit timeout")
                self._commit_cv.wait(timeout=min(remaining, 0.2))

    def committed_entries(
        self, from_index: int
    ) -> List[Tuple[int, str, Dict[str, Any]]]:
        """Committed log entries with 1-based index > ``from_index`` —
        the cross-region streaming feed (multi_region.py): the raft log
        index doubles as the region's replication sequence."""
        with self._lock:
            hi = self.commit_index
            return [
                (i, self.log[i - 1]["op"], self.log[i - 1]["data"])
                for i in range(from_index + 1, hi + 1)
            ]

    # -- election --------------------------------------------------------

    def _election_timeout(self) -> float:
        lo, hi = self.config.election_timeout
        return random.uniform(lo, hi)

    def _election_loop(self) -> None:
        timeout = self._election_timeout()
        while not self._closed.is_set():
            self._closed.wait(0.05)
            with self._lock:
                state = self._state
                silent = time.monotonic() - self._last_leader_contact
            if state is Role.PRIMARY:
                self._heartbeat()
                self._closed.wait(self.config.heartbeat_interval)
            elif silent > timeout:
                self._run_election()
                timeout = self._election_timeout()

    def _run_election(self) -> None:
        with self._lock:
            self._state = Role.CANDIDATE
            self.term += 1
            self.voted_for = self.config.node_id
            term = self.term
            last_idx = len(self.log)
            last_term = self.log[-1]["term"] if self.log else 0
            self._last_leader_contact = time.monotonic()
        votes = 1
        replies = self.transport.broadcast(
            self.config.peers,
            {
                "type": "request_vote",
                "term": term,
                "candidate": self.config.node_id,
                "last_log_index": last_idx,
                "last_log_term": last_term,
            },
            timeout=max(self.config.election_timeout[0] / 2, 0.3),
        )
        for r in replies.values():
            if r is None:
                continue
            if r.get("term", 0) > term:
                with self._lock:
                    self._step_down_locked(r["term"])
                return
            if r.get("vote_granted"):
                votes += 1
        need = (len(self.config.peers) + 1) // 2 + 1
        with self._lock:
            if self._state is Role.CANDIDATE and self.term == term and votes >= need:
                self._state = Role.PRIMARY
                self.leader_id = self.config.node_id
                self._next_index = {
                    tuple(p): len(self.log) + 1 for p in self.config.peers
                }
                # no-op barrier (Raft §5.4.2 / the reference's
                # post-election no-op): _advance_commit may only commit
                # entries of the CURRENT term, so a fresh leader could
                # otherwise never commit — or apply — the tail its
                # predecessor replicated but did not finish committing
                # (an acked write would sit unapplied on the new leader
                # until the next client write). The no-op is a
                # current-term entry whose commit pulls the whole
                # prior-term tail through; appliers skip the unknown op
                # (decode_op_args whitelists, _apply_committed isolates)
                self.log.append({"term": self.term, "op": "noop",
                                 "data": {}})
        if self.role is Role.PRIMARY:
            self._heartbeat()

    def _step_down_locked(self, term: int) -> None:
        """Caller holds the lock. ``voted_for`` is cleared ONLY when the
        term actually increases: a candidate demoted at an equal term must
        keep its vote record or it could grant a second vote in the same
        term (one-vote-per-term safety; reference raft.go:1084 clears
        votedFor only on a strictly higher request term)."""
        if term > self.term:
            self.term = term
            self.voted_for = None
        self._state = Role.STANDBY

    # -- replication -----------------------------------------------------

    def _entries_for(self, peer: Tuple[str, int]) -> ClusterMessage:
        """Caller holds the lock."""
        nxt = self._next_index.get(peer, len(self.log) + 1)
        prev_idx = nxt - 1
        prev_term = self.log[prev_idx - 1]["term"] if prev_idx >= 1 and self.log else 0
        return {
            "type": "append_entries",
            "term": self.term,
            "leader": self.config.node_id,
            "prev_log_index": prev_idx,
            "prev_log_term": prev_term,
            "entries": self.log[prev_idx:],
            "leader_commit": self.commit_index,
        }

    def _heartbeat(self) -> None:
        self._replicate_once()

    def _replicate_once(self) -> None:
        with self._lock:
            if self._state is not Role.PRIMARY:
                return
            peers = [tuple(p) for p in self.config.peers]
            msgs = {p: self._entries_for(p) for p in peers}
            term = self.term
        match_counts: Dict[int, int] = {}
        for p in peers:
            try:
                r = self.transport.request(
                    p, msgs[p], timeout=self.config.heartbeat_interval
                )
            except ConnectionError:
                continue
            if r.get("term", 0) > term:
                with self._lock:
                    self._step_down_locked(r["term"])
                return
            with self._lock:
                if r.get("ok"):
                    matched = r.get("match_index", 0)
                    self._next_index[p] = matched + 1
                    match_counts[matched] = match_counts.get(matched, 0) + 1
                else:
                    # log inconsistency: back off and retry next round
                    self._next_index[p] = max(1, self._next_index.get(p, 1) - 1)
        self._advance_commit(match_counts)

    def _advance_commit(self, match_counts: Dict[int, int]) -> None:
        with self._commit_cv:
            if self._state is not Role.PRIMARY:
                return
            if not self.config.peers:
                # single-node cluster: the leader alone is the majority
                self.commit_index = len(self.log)
                self._apply_committed()
                self._commit_cv.notify_all()
                return
            need = (len(self.config.peers) + 1) // 2 + 1
            for idx in sorted(match_counts, reverse=True):
                # count of replicas (leader + peers at >= idx)
                replicas = 1 + sum(
                    c for m, c in match_counts.items() if m >= idx
                )
                if (
                    idx > self.commit_index
                    and replicas >= need
                    and self.log[idx - 1]["term"] == self.term
                ):
                    self.commit_index = idx
                    break
            self._apply_committed()
            self._commit_cv.notify_all()

    def _apply_committed(self) -> None:
        """Caller holds the lock."""
        while self.last_applied < self.commit_index:
            entry = self.log[self.last_applied]
            self.last_applied += 1
            try:
                self.apply_fn(entry["op"], entry["data"])
            except Exception:
                pass  # state-machine apply must not wedge consensus

    # -- handlers (directly callable in tests) ---------------------------

    def handle_request_vote(self, msg: ClusterMessage) -> ClusterMessage:
        with self._lock:
            term = msg.get("term", 0)
            if term < self.term:
                return {"term": self.term, "vote_granted": False}
            if term > self.term:
                self._step_down_locked(term)
            up_to_date = (
                msg.get("last_log_term", 0),
                msg.get("last_log_index", 0),
            ) >= (
                self.log[-1]["term"] if self.log else 0,
                len(self.log),
            )
            if (
                self.voted_for in (None, msg.get("candidate"))
                and up_to_date
            ):
                self.voted_for = msg.get("candidate")
                self._last_leader_contact = time.monotonic()
                return {"term": self.term, "vote_granted": True}
            return {"term": self.term, "vote_granted": False}

    def handle_append_entries(self, msg: ClusterMessage) -> ClusterMessage:
        with self._commit_cv:
            term = msg.get("term", 0)
            if term < self.term:
                return {"term": self.term, "ok": False}
            if term > self.term or self._state is not Role.STANDBY:
                self._step_down_locked(term)
            self.term = term
            self.leader_id = msg.get("leader")
            self._last_leader_contact = time.monotonic()

            prev_idx = msg.get("prev_log_index", 0)
            prev_term = msg.get("prev_log_term", 0)
            if prev_idx > len(self.log):
                return {"term": self.term, "ok": False}
            if prev_idx >= 1 and self.log[prev_idx - 1]["term"] != prev_term:
                return {"term": self.term, "ok": False}
            # append entries, truncating only on an actual term conflict
            # (a stale/heartbeat AppendEntries must never drop good
            # entries past prev_idx)
            entries = msg.get("entries", [])
            idx = prev_idx
            for e in entries:
                if idx < len(self.log):
                    if self.log[idx]["term"] != e.get("term"):
                        self.log = self.log[:idx]
                        self.log.append(e)
                else:
                    self.log.append(e)
                idx += 1
            match_index = prev_idx + len(entries)
            leader_commit = msg.get("leader_commit", 0)
            if leader_commit > self.commit_index:
                self.commit_index = min(leader_commit, len(self.log))
                self._apply_committed()
            return {"term": self.term, "ok": True, "match_index": match_index}
