"""ReplicatedEngine: storage decorator routing writes through a Replicator.

Reference: pkg/replication/replicated_engine.go — writes go through
Replicator.Apply (replicator.go:53) so they are sequenced/streamed to
replicas; reads hit the local engine. The op/data vocabulary matches the
WAL record format (storage/wal_engine.py apply_record) so followers can
replay the stream through the identical code path used for crash
recovery.
"""

from __future__ import annotations

from typing import Tuple

from nornicdb_tpu.replication.replicator import Replicator
from nornicdb_tpu.storage.types import Edge, EngineDecorator, Engine, Node


class ReplicatedEngine(EngineDecorator):
    def __init__(self, inner: Engine, replicator: Replicator):
        super().__init__(inner)
        self.replicator = replicator

    # -- mutations route through the replicator --------------------------

    def create_node(self, node: Node) -> None:
        self.replicator.apply("create_node", node.to_dict())

    def update_node(self, node: Node) -> None:
        self.replicator.apply("update_node", node.to_dict())

    def delete_node(self, node_id: str) -> None:
        self.replicator.apply("delete_node", {"id": node_id})

    def create_edge(self, edge: Edge) -> None:
        self.replicator.apply("create_edge", edge.to_dict())

    def update_edge(self, edge: Edge) -> None:
        self.replicator.apply("update_edge", edge.to_dict())

    def delete_edge(self, edge_id: str) -> None:
        self.replicator.apply("delete_edge", {"id": edge_id})

    def delete_by_prefix(self, prefix: str) -> Tuple[int, int]:
        # count what will go for the caller, then replicate the logical op
        n = sum(1 for node in self.inner.all_nodes() if node.id.startswith(prefix))
        e = sum(1 for edge in self.inner.all_edges() if edge.id.startswith(prefix))
        self.replicator.apply("delete_by_prefix", {"prefix": prefix})
        return n, e
