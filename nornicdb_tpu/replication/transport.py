"""Cluster transport: length-prefixed JSON request/response over TCP.

Reference: pkg/replication/transport.go:53-158 (ClusterTransport /
ClusterMessage / MessageHandler), connection management (transport.go:375+),
TLS (transport_security.go). Frame format: ``uint32 big-endian payload
length | JSON payload``. Every request gets a response frame (possibly an
empty ack) so callers can implement quorum waits.

Handlers are registered per message type and run on the connection's
reader thread; they must be fast or hand off to their own executor.
"""

from __future__ import annotations

import json
import socket
import socketserver
import ssl
import struct
import threading
from typing import Any, Callable, Dict, Optional, Tuple

ClusterMessage = Dict[str, Any]
MessageHandler = Callable[[ClusterMessage], Optional[ClusterMessage]]

_LEN = struct.Struct(">I")
MAX_FRAME = 64 * 1024 * 1024


class TransportError(ConnectionError):
    pass


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise TransportError("connection closed mid-frame")
        buf += chunk
    return buf


def read_frame(sock: socket.socket) -> ClusterMessage:
    (length,) = _LEN.unpack(_read_exact(sock, 4))
    if length > MAX_FRAME:
        raise TransportError(f"frame too large: {length}")
    from nornicdb_tpu.query.temporal_types import decode_map

    # revive tagged temporal/point values in the single parse pass so
    # replica applies store the same typed property values as the primary
    return json.loads(_read_exact(sock, length).decode("utf-8"),
                      object_hook=decode_map)


def write_frame(sock: socket.socket, msg: ClusterMessage) -> None:
    from nornicdb_tpu.query.temporal_types import encode_value

    payload = json.dumps(msg, separators=(",", ":"),
                         default=encode_value).encode("utf-8")
    sock.sendall(_LEN.pack(len(payload)) + payload)


class _Conn(socketserver.BaseRequestHandler):
    def handle(self) -> None:
        transport: "ClusterTransport" = self.server.transport  # type: ignore[attr-defined]
        sock = self.request
        try:
            while not transport._closed.is_set():
                msg = read_frame(sock)
                resp = transport._dispatch(msg)
                write_frame(sock, resp if resp is not None else {"ok": True})
        except (TransportError, OSError, json.JSONDecodeError):
            pass


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class ClusterTransport:
    """One node's endpoint in the cluster mesh. Thread-safe.

    - ``register_handler(type, fn)`` — serve requests of that type.
    - ``request(addr, msg)`` — synchronous RPC to a peer (pooled conns).
    - ``broadcast(addrs, msg)`` — best-effort fan-out, returns replies.
    """

    def __init__(
        self,
        node_id: str,
        listen_addr: Tuple[str, int] = ("127.0.0.1", 0),
        ssl_server: Optional[ssl.SSLContext] = None,
        ssl_client: Optional[ssl.SSLContext] = None,
    ):
        self.node_id = node_id
        self._handlers: Dict[str, MessageHandler] = {}
        self._pool: Dict[Tuple[str, int], socket.socket] = {}
        self._pool_lock = threading.Lock()
        self._handlers_lock = threading.Lock()
        self._closed = threading.Event()
        self._ssl_server = ssl_server
        self._ssl_client = ssl_client
        self._server = _Server(listen_addr, _Conn, bind_and_activate=False)
        self._server.transport = self  # type: ignore[attr-defined]
        if ssl_server is not None:
            self._server.socket = ssl_server.wrap_socket(
                self._server.socket, server_side=True
            )
        self._server.server_bind()
        self._server.server_activate()
        self._thread: Optional[threading.Thread] = None

    @property
    def addr(self) -> Tuple[str, int]:
        return self._server.socket.getsockname()[:2]

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name=f"cluster-{self.node_id}",
        )
        self._thread.start()

    def close(self) -> None:
        self._closed.set()
        self._server.shutdown()
        self._server.server_close()
        with self._pool_lock:
            for sock in self._pool.values():
                try:
                    sock.close()
                except OSError:
                    pass
            self._pool.clear()

    def register_handler(self, msg_type: str, fn: MessageHandler) -> None:
        with self._handlers_lock:
            self._handlers[msg_type] = fn

    def _dispatch(self, msg: ClusterMessage) -> Optional[ClusterMessage]:
        with self._handlers_lock:
            fn = self._handlers.get(msg.get("type", ""))
        if fn is None:
            return {"ok": False, "error": f"no handler for {msg.get('type')}"}
        try:
            return fn(msg)
        except Exception as e:  # handler bugs become error replies
            return {"ok": False, "error": f"{type(e).__name__}: {e}"}

    # -- client side -----------------------------------------------------

    def _connect(self, addr: Tuple[str, int], timeout: float) -> socket.socket:
        sock = socket.create_connection(addr, timeout=timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        if self._ssl_client is not None:
            sock = self._ssl_client.wrap_socket(sock, server_hostname=addr[0])
        return sock

    def request(
        self,
        addr: Tuple[str, int],
        msg: ClusterMessage,
        timeout: float = 5.0,
    ) -> ClusterMessage:
        """Send one message and wait for its response frame. Connections
        are pooled per peer; a broken pooled connection is retried once
        on a fresh socket."""
        msg = dict(msg)
        msg.setdefault("from", self.node_id)
        key = tuple(addr)
        for attempt in (0, 1):
            with self._pool_lock:
                sock = self._pool.pop(key, None)
            try:
                if sock is None:
                    sock = self._connect(key, timeout)
                sock.settimeout(timeout)
                write_frame(sock, msg)
                resp = read_frame(sock)
                with self._pool_lock:
                    if not self._closed.is_set():
                        self._pool[key] = sock
                return resp
            except (OSError, TransportError, json.JSONDecodeError):
                if sock is not None:
                    try:
                        sock.close()
                    except OSError:
                        pass
                if attempt == 1:
                    raise TransportError(f"request to {addr} failed")
        raise TransportError(f"request to {addr} failed")  # unreachable

    def broadcast(
        self,
        addrs: list,
        msg: ClusterMessage,
        timeout: float = 5.0,
    ) -> Dict[Tuple[str, int], Optional[ClusterMessage]]:
        """Parallel best-effort fan-out; unreachable peers map to None."""
        results: Dict[Tuple[str, int], Optional[ClusterMessage]] = {}
        lock = threading.Lock()

        def one(addr):
            try:
                r = self.request(tuple(addr), msg, timeout)
            except TransportError:
                r = None
            with lock:
                results[tuple(addr)] = r

        threads = [
            threading.Thread(target=one, args=(a,), daemon=True) for a in addrs
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout + 1.0)
        return results
