"""Cluster transport: length-prefixed JSON request/response over TCP.

Reference: pkg/replication/transport.go:53-158 (ClusterTransport /
ClusterMessage / MessageHandler), connection management (transport.go:375+),
TLS (transport_security.go). Frame format: ``uint32 big-endian payload
length | JSON payload``. Every request gets a response frame (possibly an
empty ack) so callers can implement quorum waits.

Handlers are registered per message type and run on the connection's
reader thread; they must be fast or hand off to their own executor.
"""

from __future__ import annotations

import json
import socket
import socketserver
import ssl
import struct
import threading
from typing import Any, Callable, Dict, Optional, Tuple

ClusterMessage = Dict[str, Any]
MessageHandler = Callable[[ClusterMessage], Optional[ClusterMessage]]

_LEN = struct.Struct(">I")
MAX_FRAME = 64 * 1024 * 1024

# two-plane partition (ISSUE 16; reference: SURVEY §2.8 — Raft/HA control
# on host TCP, bulk index/WAL sync on the data plane): message types in
# this set ride the control channel, everything else (wal_batch, wal_sync
# and its snapshot payloads) rides the bulk data channel so a multi-MB
# snapshot ship can never head-of-line-block a heartbeat or fence.
CONTROL_TYPES = frozenset({"heartbeat", "fence", "plane_info"})


class TransportError(ConnectionError):
    pass


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise TransportError("connection closed mid-frame")
        buf += chunk
    return buf


def read_frame(sock: socket.socket) -> ClusterMessage:
    (length,) = _LEN.unpack(_read_exact(sock, 4))
    if length > MAX_FRAME:
        raise TransportError(f"frame too large: {length}")
    from nornicdb_tpu.query.temporal_types import decode_map

    # revive tagged temporal/point values in the single parse pass so
    # replica applies store the same typed property values as the primary
    return json.loads(_read_exact(sock, length).decode("utf-8"),
                      object_hook=decode_map)


def write_frame(sock: socket.socket, msg: ClusterMessage) -> None:
    from nornicdb_tpu.query.temporal_types import encode_value

    payload = json.dumps(msg, separators=(",", ":"),
                         default=encode_value).encode("utf-8")
    sock.sendall(_LEN.pack(len(payload)) + payload)


class _Conn(socketserver.BaseRequestHandler):
    def handle(self) -> None:
        transport: "ClusterTransport" = self.server.transport  # type: ignore[attr-defined]
        sock = self.request
        try:
            while not transport._closed.is_set():
                msg = read_frame(sock)
                resp = transport._dispatch(msg)
                write_frame(sock, resp if resp is not None else {"ok": True})
        except (TransportError, OSError, json.JSONDecodeError):
            pass


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class ClusterTransport:
    """One node's endpoint in the cluster mesh. Thread-safe.

    - ``register_handler(type, fn)`` — serve requests of that type.
    - ``request(addr, msg)`` — synchronous RPC to a peer (pooled conns).
    - ``broadcast(addrs, msg)`` — best-effort fan-out, returns replies.
    """

    def __init__(
        self,
        node_id: str,
        listen_addr: Tuple[str, int] = ("127.0.0.1", 0),
        ssl_server: Optional[ssl.SSLContext] = None,
        ssl_client: Optional[ssl.SSLContext] = None,
    ):
        self.node_id = node_id
        self._handlers: Dict[str, MessageHandler] = {}
        self._pool: Dict[Tuple[str, int], socket.socket] = {}
        self._pool_lock = threading.Lock()
        self._handlers_lock = threading.Lock()
        self._closed = threading.Event()
        self._ssl_server = ssl_server
        self._ssl_client = ssl_client
        self._server = _Server(listen_addr, _Conn, bind_and_activate=False)
        self._server.transport = self  # type: ignore[attr-defined]
        if ssl_server is not None:
            self._server.socket = ssl_server.wrap_socket(
                self._server.socket, server_side=True
            )
        self._server.server_bind()
        self._server.server_activate()
        self._thread: Optional[threading.Thread] = None

    @property
    def addr(self) -> Tuple[str, int]:
        return self._server.socket.getsockname()[:2]

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name=f"cluster-{self.node_id}",
        )
        self._thread.start()

    def close(self) -> None:
        self._closed.set()
        self._server.shutdown()
        self._server.server_close()
        with self._pool_lock:
            for sock in self._pool.values():
                try:
                    sock.close()
                except OSError:
                    pass
            self._pool.clear()

    def register_handler(self, msg_type: str, fn: MessageHandler) -> None:
        with self._handlers_lock:
            self._handlers[msg_type] = fn

    def _dispatch(self, msg: ClusterMessage) -> Optional[ClusterMessage]:
        with self._handlers_lock:
            fn = self._handlers.get(msg.get("type", ""))
        if fn is None:
            return {"ok": False, "error": f"no handler for {msg.get('type')}"}
        try:
            return fn(msg)
        except Exception as e:  # handler bugs become error replies
            return {"ok": False, "error": f"{type(e).__name__}: {e}"}

    # -- client side -----------------------------------------------------

    def _connect(self, addr: Tuple[str, int], timeout: float) -> socket.socket:
        sock = socket.create_connection(addr, timeout=timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        if self._ssl_client is not None:
            sock = self._ssl_client.wrap_socket(sock, server_hostname=addr[0])
        return sock

    def request(
        self,
        addr: Tuple[str, int],
        msg: ClusterMessage,
        timeout: float = 5.0,
    ) -> ClusterMessage:
        """Send one message and wait for its response frame. Connections
        are pooled per peer; a broken pooled connection is retried once
        on a fresh socket."""
        msg = dict(msg)
        msg.setdefault("from", self.node_id)
        key = tuple(addr)
        for attempt in (0, 1):
            with self._pool_lock:
                sock = self._pool.pop(key, None)
            try:
                if sock is None:
                    sock = self._connect(key, timeout)
                sock.settimeout(timeout)
                write_frame(sock, msg)
                resp = read_frame(sock)
                with self._pool_lock:
                    if not self._closed.is_set():
                        self._pool[key] = sock
                return resp
            except (OSError, TransportError, json.JSONDecodeError):
                if sock is not None:
                    try:
                        sock.close()
                    except OSError:
                        pass
                if attempt == 1:
                    raise TransportError(f"request to {addr} failed")
        raise TransportError(f"request to {addr} failed")  # unreachable

    def broadcast(
        self,
        addrs: list,
        msg: ClusterMessage,
        timeout: float = 5.0,
    ) -> Dict[Tuple[str, int], Optional[ClusterMessage]]:
        """Parallel best-effort fan-out; unreachable peers map to None."""
        results: Dict[Tuple[str, int], Optional[ClusterMessage]] = {}
        lock = threading.Lock()

        def one(addr):
            try:
                r = self.request(tuple(addr), msg, timeout)
            except TransportError:
                r = None
            with lock:
                results[tuple(addr)] = r

        threads = [
            threading.Thread(target=one, args=(a,), daemon=True) for a in addrs
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout + 1.0)
        return results


class DualPlaneTransport:
    """Two-plane cluster endpoint: control and bulk data on separate
    channels (ISSUE 16).

    The control plane carries the small latency-critical messages —
    heartbeats, epochs, fencing — while WAL batches and snapshot ships
    go over a second TCP endpoint, so replication bulk can saturate its
    socket without delaying failure detection. Peers are still addressed
    by a single (control) address: the data-plane address is discovered
    over the control channel via a built-in ``plane_info`` exchange and
    cached. A peer that answers ``plane_info`` with an error (an older
    single-plane :class:`ClusterTransport`) degrades gracefully — bulk
    falls back to its control address.

    API-compatible with :class:`ClusterTransport` (``register_handler``
    / ``request`` / ``broadcast`` / ``addr``), so HAPrimary/HAStandby
    work unchanged on either.
    """

    def __init__(
        self,
        node_id: str,
        listen_addr: Tuple[str, int] = ("127.0.0.1", 0),
        data_listen_addr: Tuple[str, int] = ("127.0.0.1", 0),
        ssl_server: Optional[ssl.SSLContext] = None,
        ssl_client: Optional[ssl.SSLContext] = None,
    ):
        self.node_id = node_id
        self.control = ClusterTransport(
            node_id, listen_addr, ssl_server, ssl_client)
        self.data = ClusterTransport(
            node_id, data_listen_addr, ssl_server, ssl_client)
        self._peer_data: Dict[Tuple[str, int], Tuple[str, int]] = {}
        self._peer_lock = threading.Lock()
        for t in (self.control, self.data):
            t.register_handler("plane_info", self._handle_plane_info)

    def _handle_plane_info(self, msg: ClusterMessage) -> ClusterMessage:
        return {
            "ok": True,
            "control_addr": list(self.control.addr),
            "data_addr": list(self.data.addr),
        }

    @property
    def addr(self) -> Tuple[str, int]:
        """The node's advertised address — the control endpoint. Peers
        configured with this address reach both planes (data-plane addr
        is exchanged over it)."""
        return self.control.addr

    @property
    def data_addr(self) -> Tuple[str, int]:
        return self.data.addr

    def start(self) -> None:
        self.control.start()
        self.data.start()

    def close(self) -> None:
        self.control.close()
        self.data.close()

    def register_handler(self, msg_type: str, fn: MessageHandler) -> None:
        # handlers go on BOTH planes: routing of *outgoing* traffic is
        # what creates the split; an older single-plane peer that sends
        # bulk to our control address must still be served.
        self.control.register_handler(msg_type, fn)
        self.data.register_handler(msg_type, fn)

    def _resolve_data(self, addr: Tuple[str, int],
                      timeout: float) -> Tuple[str, int]:
        """Map a peer's control address to its data-plane address via a
        cached ``plane_info`` exchange; single-plane peers map to their
        own (control) address."""
        key = tuple(addr)
        with self._peer_lock:
            hit = self._peer_data.get(key)
        if hit is not None:
            return hit
        mapped = key
        try:
            resp = self.control.request(
                key, {"type": "plane_info"}, timeout=timeout)
            if resp.get("ok") and resp.get("data_addr"):
                mapped = tuple(resp["data_addr"])  # type: ignore[assignment]
        except TransportError:
            return key  # unreachable: do not cache, retry next send
        with self._peer_lock:
            self._peer_data[key] = mapped
        return mapped

    def forget_peer(self, addr: Tuple[str, int]) -> None:
        """Drop the cached data-plane mapping (peer restarted on a new
        port)."""
        with self._peer_lock:
            self._peer_data.pop(tuple(addr), None)

    def request(
        self,
        addr: Tuple[str, int],
        msg: ClusterMessage,
        timeout: float = 5.0,
    ) -> ClusterMessage:
        if msg.get("type") in CONTROL_TYPES:
            return self.control.request(addr, msg, timeout)
        data_addr = self._resolve_data(tuple(addr), timeout)
        try:
            return self.data.request(data_addr, msg, timeout)
        except TransportError:
            # peer may have restarted with a new data port — re-resolve
            # once through the (stable) control address before giving up
            self.forget_peer(addr)
            fresh = self._resolve_data(tuple(addr), timeout)
            if fresh == data_addr:
                raise
            return self.data.request(fresh, msg, timeout)

    def broadcast(
        self,
        addrs: list,
        msg: ClusterMessage,
        timeout: float = 5.0,
    ) -> Dict[Tuple[str, int], Optional[ClusterMessage]]:
        """Plane-routed fan-out. Results are keyed by the *configured*
        (control) addresses so callers' quorum accounting is unchanged."""
        if msg.get("type") in CONTROL_TYPES:
            return self.control.broadcast(addrs, msg, timeout)
        mapping = {tuple(a): self._resolve_data(tuple(a), timeout)
                   for a in addrs}
        raw = self.data.broadcast(list(mapping.values()), msg, timeout)
        return {ctrl: raw.get(data) for ctrl, data in mapping.items()}
