"""Device data plane: JAX/XLA/Pallas kernels over HBM-resident matrices.

This package replaces the reference's four GPU backends
(Metal/CUDA/Vulkan/OpenCL — pkg/gpu) and its SIMD layer (pkg/simd) with
ONE code path: jitted XLA computations (+ Pallas kernels for fused ops)
that run identically on TPU and on the CPU backend used as the test
double (reference parity-test pattern: pkg/gpu/*_stub_test.go).
"""

from nornicdb_tpu.ops.similarity import (  # noqa: F401
    cosine_topk,
    cosine_topk_chunked,
    l2_normalize,
    pad_dim,
)
from nornicdb_tpu.ops.kmeans import KMeansResult, kmeans_assign, kmeans_fit  # noqa: F401
