"""FastRP node embeddings (Fast Random Projection).

Reference: pkg/cypher/fastrp.go (802 LoC, gds.fastRP.stream over a
projected graph). TPU-first redesign: instead of the reference's
per-node Go loops, propagation is a handful of dense array ops —
scatter-add over the edge arrays (the same columnar layout as
query/columnar.py) with degree normalization, which XLA/numpy vectorize
wholesale. Algorithm per the FastRP paper: very sparse random projection
init, L2-normalized neighbor-averaging iterations, weighted sum.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


def _normalize_rows(m: np.ndarray) -> np.ndarray:
    norms = np.linalg.norm(m, axis=1, keepdims=True)
    return m / np.maximum(norms, 1e-12)


def fastrp_embeddings(
    n_nodes: int,
    src: np.ndarray,
    dst: np.ndarray,
    dim: int = 64,
    iteration_weights: Sequence[float] = (0.0, 1.0, 1.0),
    normalization_strength: float = 0.0,
    seed: int = 42,
    sparsity: int = 3,
) -> np.ndarray:
    """[n_nodes, dim] float32 embeddings.

    src/dst: int arrays of edge endpoints (node row indices); edges are
    treated as undirected (both directions propagate), matching
    gds.fastRP defaults.
    """
    rng = np.random.default_rng(seed)
    # very sparse random projection: +/- sqrt(s) w.p. 1/2s each, else 0
    s = float(sparsity)
    u = rng.random((n_nodes, dim))
    r = np.zeros((n_nodes, dim), np.float32)
    r[u < 1.0 / (2 * s)] = np.sqrt(s)
    r[u > 1.0 - 1.0 / (2 * s)] = -np.sqrt(s)

    deg = np.zeros(n_nodes, np.float64)
    np.add.at(deg, src, 1.0)
    np.add.at(deg, dst, 1.0)
    # degree scaling d^beta (normalization strength, gds default 0)
    with np.errstate(divide="ignore"):
        scale = np.where(deg > 0, deg ** normalization_strength, 0.0)
    inv_deg = np.where(deg > 0, 1.0 / deg, 0.0)

    def propagate(h: np.ndarray) -> np.ndarray:
        out = np.zeros_like(h)
        np.add.at(out, src, h[dst])
        np.add.at(out, dst, h[src])
        out *= inv_deg[:, None]  # mean over neighbors
        out *= scale[:, None]
        return out

    emb = np.zeros((n_nodes, dim), np.float32)
    h = r
    for w in iteration_weights:
        h = propagate(h)
        h = _normalize_rows(h).astype(np.float32)
        if w:
            emb += np.float32(w) * h
    return _normalize_rows(emb).astype(np.float32)


def fastrp_embeddings_device(
    n_nodes: int,
    src: np.ndarray,
    dst: np.ndarray,
    dim: int = 64,
    iteration_weights: Sequence[float] = (0.0, 1.0, 1.0),
    normalization_strength: float = 0.0,
    seed: int = 42,
    sparsity: int = 3,
) -> np.ndarray:
    """Device FastRP: the same algorithm as :func:`fastrp_embeddings`
    run as one jitted matmul/segment-sum chain. The very-sparse random
    init is generated on the HOST with the identical rng stream and
    transferred, so the two paths start from the same projection; the
    propagation then runs in f32 on device (the host path accumulates
    the degree column in f64), so embeddings agree to f32 tolerance —
    the parity contract is cosine-level, not bitwise, and the
    background plane's brute-index consumer treats it that way."""
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    s = float(sparsity)
    u = rng.random((n_nodes, dim))
    r = np.zeros((n_nodes, dim), np.float32)
    r[u < 1.0 / (2 * s)] = np.sqrt(s)
    r[u > 1.0 - 1.0 / (2 * s)] = -np.sqrt(s)
    if n_nodes == 0:
        return r
    weights = tuple(float(w) for w in iteration_weights)

    @jax.jit
    def run(r0, src_d, dst_d):
        both_src = jnp.concatenate([src_d, dst_d])
        both_dst = jnp.concatenate([dst_d, src_d])
        deg = jax.ops.segment_sum(
            jnp.ones_like(both_src, jnp.float32), both_src,
            num_segments=n_nodes)
        scale = jnp.where(deg > 0, deg ** normalization_strength, 0.0)
        inv_deg = jnp.where(deg > 0, 1.0 / deg, 0.0)

        def propagate(h):
            out = jax.ops.segment_sum(h[both_dst], both_src,
                                      num_segments=n_nodes)
            return out * (inv_deg * scale)[:, None]

        def norm_rows(m):
            return m / jnp.maximum(
                jnp.linalg.norm(m, axis=1, keepdims=True), 1e-12)

        emb = jnp.zeros_like(r0)
        h = r0
        for w in weights:
            h = norm_rows(propagate(h))
            if w:
                emb = emb + jnp.float32(w) * h
        return norm_rows(emb)

    if len(src) == 0:
        return _normalize_rows(np.zeros((n_nodes, dim), np.float32)) \
            .astype(np.float32)
    return np.asarray(run(jnp.asarray(r),
                          jnp.asarray(src, jnp.int32),
                          jnp.asarray(dst, jnp.int32)))


class GdsGraphCatalog:
    """In-memory projected-graph catalog (reference: gds.graph.project /
    list / drop, fastrp.go:8-26)."""

    def __init__(self):
        self._graphs: Dict[str, Dict] = {}

    def project(self, storage, name: str, node_label: str,
                rel_type: str) -> Dict:
        if name in self._graphs:
            raise ValueError(f"graph {name!r} already exists")
        if node_label in ("*", "", None):
            nodes = list(storage.all_nodes())
        else:
            nodes = storage.get_nodes_by_label(node_label)
        row_of = {n.id: i for i, n in enumerate(nodes)}
        src: List[int] = []
        dst: List[int] = []
        edges = (storage.all_edges() if rel_type in ("*", "", None)
                 else storage.get_edges_by_type(rel_type))
        n_rels = 0
        for e in edges:
            a = row_of.get(e.start_node)
            b = row_of.get(e.end_node)
            if a is None or b is None:
                continue
            src.append(a)
            dst.append(b)
            n_rels += 1
        g = {
            "name": name,
            "node_ids": [n.id for n in nodes],
            "src": np.asarray(src, np.int64),
            "dst": np.asarray(dst, np.int64),
            "nodeCount": len(nodes),
            "relationshipCount": n_rels,
            "nodeProjection": node_label or "*",
            "relationshipProjection": rel_type or "*",
        }
        self._graphs[name] = g
        return g

    def get(self, name: str) -> Optional[Dict]:
        return self._graphs.get(name)

    def drop(self, name: str) -> Optional[Dict]:
        return self._graphs.pop(name, None)

    def list(self) -> List[Dict]:
        return list(self._graphs.values())

    def fastrp(self, name: str, dim: int = 64,
               iteration_weights: Sequence[float] = (0.0, 1.0, 1.0),
               normalization_strength: float = 0.0,
               seed: int = 42) -> Tuple[List[str], np.ndarray]:
        g = self._graphs.get(name)
        if g is None:
            raise KeyError(f"graph {name!r} not found; "
                           "CALL gds.graph.project(...) first")
        emb = fastrp_embeddings(
            g["nodeCount"], g["src"], g["dst"], dim=dim,
            iteration_weights=iteration_weights,
            normalization_strength=normalization_strength, seed=seed,
        )
        return g["node_ids"], emb
