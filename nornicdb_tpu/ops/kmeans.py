"""On-device k-means for IVF cluster routing.

Replaces the reference's Metal k-means kernel suite
(kmeans_kernels_darwin.metal:71-370: compute_distances, assign,
zero/accumulate/finalize centroids, drift, kmeans++ distances) and the Go
ClusterIndex driver (pkg/gpu/kmeans.go:146-905). TPU design:

- assignment = one [N,K] matmul (argmax over centroid dots) — MXU;
- centroid update = one-hot [N,K]^T @ X matmul + count normalization —
  also MXU, no scatter;
- the whole Lloyd loop runs inside one jit with lax.while_loop, exiting
  early on centroid drift below tolerance (reference checkConvergence);
- kmeans++ and *seeded* init (BM25-discriminative docs as preferred
  seeds — reference kmeans.go:409 initCentroidsKMeansPlusPlusSeededFromVectors,
  SetPreferredSeedIndices :464) cut iterations ~40% (CHANGELOG 1.0.12).
"""

from __future__ import annotations

import functools
import math
from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@dataclass
class KMeansResult:
    centroids: np.ndarray  # [K, D], L2-normalized
    assignments: np.ndarray  # [N] int32
    iterations: int
    converged: bool
    inertia: float


def optimal_k(n: int) -> int:
    """Heuristic cluster count = f(n) (reference: kmeans.go optimalK)."""
    if n < 1000:
        return max(1, n // 100)
    return max(8, min(4096, int(math.sqrt(n / 2))))


def euclid_kmeans(
    x: np.ndarray, k: int, iters: int = 25,
    seed_ids: Optional[Sequence[int]] = None, seed: int = 0,
) -> Tuple[np.ndarray, np.ndarray]:
    """Euclidean Lloyd with kmeans++ init (optionally seeded rows first).

    The SHARED codebook trainer: host IVF-PQ (search/ivfpq.py) and the
    device PQ plane (search/device_quant.py) both train through this
    one implementation, so their codebooks are bit-identical given the
    same sample/seed. It stays separate from :func:`kmeans_fit`, which
    normalizes rows (cosine clustering) — that would corrupt PQ
    subvector codebooks, which need true L2 geometry."""
    rng = np.random.default_rng(seed)
    n = len(x)
    k = max(1, min(k, n))
    chosen: list = list(dict.fromkeys(
        int(i) for i in (seed_ids or []) if 0 <= int(i) < n))[:k]
    if not chosen:
        chosen = [int(rng.integers(n))]
    # incremental k-means++: keep the running min-distance-to-chosen
    # array and update it against ONLY the newest center — O(k*n*d),
    # not O(k^2*n*d) (the recompute-all version took ~9 min for one
    # 256-code codebook at n=10k)
    d2 = np.full(n, np.inf, dtype=np.float64)
    for i in chosen:
        d2 = np.minimum(d2, np.sum((x - x[i]) ** 2, axis=1))
    while len(chosen) < k:
        total = d2.sum()
        if total <= 1e-12:
            # all remaining points coincide with a centroid (duplicate/
            # constant subvectors): fall back to uniform picks
            nxt = int(rng.integers(n))
        else:
            nxt = int(rng.choice(n, p=d2 / total))
        chosen.append(nxt)
        d2 = np.minimum(d2, np.sum((x - x[nxt]) ** 2, axis=1))
    cent = x[chosen].copy()
    assign = np.zeros(n, dtype=np.int64)
    for it in range(iters):
        dist = (
            np.sum(x**2, axis=1, keepdims=True)
            - 2.0 * x @ cent.T
            + np.sum(cent**2, axis=1)[None, :]
        )
        new_assign = np.argmin(dist, axis=1)
        if it > 0 and np.array_equal(new_assign, assign):
            break
        assign = new_assign
        for j in range(k):
            members = x[assign == j]
            if len(members):
                cent[j] = members.mean(axis=0)
    return cent.astype(np.float32), assign


def train_subspace_codebooks(
    sample: np.ndarray, m: int, n_codes: int = 256,
) -> np.ndarray:
    """Per-subspace PQ codebooks ``[M, n_codes, D/M]`` over (residual or
    raw) rows — the single training routine behind both the host IVF-PQ
    codebooks and the device PQ plane. Short codebooks pad by repeating
    the last entry so the output shape is fixed."""
    n, d = sample.shape
    if d % m != 0:
        raise ValueError(f"dims {d} not divisible by M={m}")
    sub = sample.reshape(n, m, d // m)
    codes_k = min(n_codes, n)
    books = []
    for j in range(m):
        cb, _ = euclid_kmeans(
            np.ascontiguousarray(sub[:, j, :]), codes_k, seed=j + 1)
        if cb.shape[0] < n_codes:  # pad to fixed shape
            pad = np.repeat(cb[-1:], n_codes - cb.shape[0], axis=0)
            cb = np.concatenate([cb, pad], axis=0)
        books.append(cb)
    return np.stack(books)  # [M, n_codes, D/M]


@functools.partial(jax.jit, static_argnames=("k",))
def _kmeanspp_seeded_init(
    x: jnp.ndarray,  # [N, D] normalized
    valid: jnp.ndarray,  # [N] bool
    seed_scores: jnp.ndarray,  # [N] float — preferred-seed bonus (0 if none)
    key: jax.Array,
    k: int,
) -> jnp.ndarray:
    """k-means++ with optional preferred seeds: the classic D^2 weighting is
    multiplied by exp(seed_score), so lexically-discriminative docs (BM25
    seeds) win ties and anchor the initial centroids."""
    n, d = x.shape

    def pick(carry, _):
        centroids, n_chosen, min_d2, key = carry
        key, sub = jax.random.split(key)
        w = min_d2 * jnp.exp(seed_scores)
        w = jnp.where(valid, w, 0.0)
        # guard: all-zero weights -> uniform over valid
        total = jnp.sum(w)
        w = jnp.where(total > 0, w, valid.astype(x.dtype))
        idx = jax.random.categorical(sub, jnp.log(w + 1e-30))
        c = x[idx]
        centroids = centroids.at[n_chosen].set(c)
        d2 = jnp.sum((x - c[None, :]) ** 2, axis=1)
        min_d2 = jnp.minimum(min_d2, d2)
        return (centroids, n_chosen + 1, min_d2, key), None

    key, sub = jax.random.split(key)
    w0 = jnp.where(valid, jnp.exp(seed_scores), 0.0)
    first = jax.random.categorical(sub, jnp.log(w0 + 1e-30))
    centroids = jnp.zeros((k, d), dtype=x.dtype).at[0].set(x[first])
    min_d2 = jnp.sum((x - x[first][None, :]) ** 2, axis=1)
    (centroids, _, _, _), _ = jax.lax.scan(
        pick, (centroids, 1, min_d2, key), None, length=k - 1
    )
    return centroids


@functools.partial(jax.jit, static_argnames=())
def kmeans_assign(
    x: jnp.ndarray, valid: jnp.ndarray, centroids: jnp.ndarray
) -> jnp.ndarray:
    """Assign each row to its nearest centroid (cosine; inputs normalized).
    Invalid rows get -1. (reference: assign kernel)"""
    sims = x @ centroids.T  # [N, K] — MXU
    a = jnp.argmax(sims, axis=1).astype(jnp.int32)
    return jnp.where(valid, a, -1)


@functools.partial(jax.jit, static_argnames=("k", "max_iters"))
def _lloyd(
    x: jnp.ndarray,  # [N, D] normalized
    valid: jnp.ndarray,  # [N]
    init_centroids: jnp.ndarray,  # [K, D]
    k: int,
    max_iters: int,
    tol: float,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    n, d = x.shape
    xv = x * valid[:, None].astype(x.dtype)

    def norm_rows(c):
        nrm = jnp.sqrt(jnp.sum(c * c, axis=1, keepdims=True))
        return c / jnp.maximum(nrm, 1e-12)

    def body(carry):
        centroids, it, drift = carry
        sims = xv @ centroids.T  # [N, K]
        a = jnp.argmax(sims, axis=1)
        onehot = jax.nn.one_hot(a, k, dtype=x.dtype) * valid[:, None].astype(x.dtype)
        sums = onehot.T @ xv  # [K, D] — MXU, replaces scatter-accumulate
        counts = jnp.sum(onehot, axis=0)  # [K]
        new_c = sums / jnp.maximum(counts[:, None], 1.0)
        # empty clusters keep their previous centroid (reference: finalize)
        new_c = jnp.where(counts[:, None] > 0, new_c, centroids)
        new_c = norm_rows(new_c)
        drift = jnp.max(jnp.sum((new_c - centroids) ** 2, axis=1))
        return new_c, it + 1, drift

    def cond(carry):
        _, it, drift = carry
        return (it < max_iters) & (drift > tol)

    centroids, iters, drift = jax.lax.while_loop(
        cond, body, (norm_rows(init_centroids), jnp.int32(0), jnp.float32(1e9))
    )
    sims = x @ centroids.T  # one post-loop [N,K] matmul for both outputs
    a = jnp.where(valid, jnp.argmax(sims, axis=1).astype(jnp.int32), -1)
    best = jnp.max(sims, axis=1)
    inertia = jnp.sum(jnp.where(valid, 1.0 - best, 0.0))
    return centroids, a, iters, inertia


def kmeans_fit(
    vectors: np.ndarray,
    k: Optional[int] = None,
    *,
    valid: Optional[np.ndarray] = None,
    preferred_seed_indices: Optional[Sequence[int]] = None,
    max_iters: int = 50,
    tol: float = 1e-6,
    seed: int = 0,
    init: str = "kmeans++",
) -> KMeansResult:
    """Fit k-means on device. ``preferred_seed_indices`` biases kmeans++
    toward those rows (the BM25-seeded init)."""
    x = jnp.asarray(vectors, dtype=jnp.float32)
    n = x.shape[0]
    n_valid = int(np.sum(valid)) if valid is not None else n
    if k is None:
        k = optimal_k(n_valid)
    # k must not exceed the number of valid rows, or init would be forced
    # to seed centroids from padding/deleted vectors
    k = max(1, min(k, n_valid))
    from nornicdb_tpu.ops.similarity import l2_normalize

    x = l2_normalize(x)
    v = (
        jnp.asarray(valid, dtype=bool)
        if valid is not None
        else jnp.ones((n,), dtype=bool)
    )
    key = jax.random.PRNGKey(seed)
    seed_scores = np.zeros((n,), dtype=np.float32)
    if preferred_seed_indices is not None and len(preferred_seed_indices) > 0:
        seed_scores[np.asarray(list(preferred_seed_indices), dtype=np.int64)] = 4.0
    if init == "random":
        key, sub = jax.random.split(key)
        probs = v.astype(jnp.float32)
        idx = jax.random.choice(
            sub, n, shape=(k,), replace=False, p=probs / jnp.sum(probs)
        )
        init_c = x[idx]
    else:
        init_c = _kmeanspp_seeded_init(x, v, jnp.asarray(seed_scores), key, k)
    centroids, a, iters, inertia = _lloyd(x, v, init_c, k, max_iters, tol)
    return KMeansResult(
        centroids=np.asarray(centroids),
        assignments=np.asarray(a),
        iterations=int(iters),
        converged=int(iters) < max_iters,
        inertia=float(inertia),
    )


@jax.jit
def reassign_single(
    vector: jnp.ndarray, centroids: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Incremental single-vector reassignment on ingest
    (reference: reassign_single kernel + kmeans.go incremental path)."""
    sims = centroids @ vector
    best = jnp.argmax(sims)
    return best.astype(jnp.int32), sims[best]
