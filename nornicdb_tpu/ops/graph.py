"""Vectorized graph algorithms over columnar edge arrays.

Reference: apoc/algo/algo.go:32 (PageRank), pkg/cypher/linkprediction.go.
TPU design: the graph is packed into flat int32 src/dst arrays (a columnar
snapshot); power iteration runs entirely on device — the scatter-add is a
`.at[].add()` which XLA lowers to an efficient sort-based segment sum.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from nornicdb_tpu.storage.types import Engine


@functools.partial(jax.jit, static_argnames=("n", "iters"))
def _pagerank_impl(
    src: jnp.ndarray,  # [E] int32
    dst: jnp.ndarray,  # [E] int32
    n: int,
    iters: int,
    damping: float = 0.85,
) -> jnp.ndarray:
    out_deg = jax.ops.segment_sum(
        jnp.ones_like(src, jnp.float32), src, num_segments=n)
    safe_deg = jnp.maximum(out_deg, 1.0)
    # sort edges by destination ONCE; every iteration's scatter then
    # becomes a sorted segment-sum (sequential HBM traffic) instead of
    # a per-iteration sort — on a real chip this took the 20-iteration
    # LDBC-scale run from ~600ms to ~1ms
    order = jnp.argsort(dst)
    dst_s = dst[order]
    src_s = src[order]

    def step(p, _):
        contrib = p / safe_deg
        # dangling mass redistributes uniformly
        dangling = jnp.sum(jnp.where(out_deg == 0, p, 0.0))
        acc = jax.ops.segment_sum(
            contrib[src_s], dst_s, num_segments=n,
            indices_are_sorted=True)
        p_new = (1.0 - damping) / n + damping * (acc + dangling / n)
        return p_new, None

    p0 = jnp.full((n,), 1.0 / n, jnp.float32)
    p, _ = jax.lax.scan(step, p0, None, length=iters)
    return p


def _pagerank_host(
    src: np.ndarray, dst: np.ndarray, n: int, iters: int, damping: float
) -> np.ndarray:
    """Host power iteration over a CSR adjacency built ONCE — ~4x a
    naive np.add.at loop at LDBC scale (the scatter is re-expressed as
    a C-speed spmv per iteration). Same math as _pagerank_impl; parity
    pinned in tests."""
    import scipy.sparse as sp

    deg = np.bincount(src, minlength=n).astype(np.float32)
    safe = np.maximum(deg, 1.0)
    adj = sp.csr_matrix(
        (np.ones(len(src), np.float32), (dst, src)), shape=(n, n))
    dangle = deg == 0
    p = np.full(n, 1.0 / n, np.float32)
    for _ in range(iters):
        contrib = p / safe
        dangling = p[dangle].sum() / n
        p = ((1.0 - damping) / n
             + damping * (adj @ contrib + dangling)).astype(np.float32)
    return p


def pagerank_arrays(
    src: np.ndarray, dst: np.ndarray, n: int, iters: int = 20,
    damping: float = 0.85, dev_src=None, dev_dst=None,
) -> np.ndarray:
    """``dev_src``/``dev_dst``: already-device-resident int32 edge
    arrays (the device graph plane's shared CSR snapshot) — passing
    them skips the per-call host->device edge-array transfer. Must
    hold the same values as ``src``/``dst``; results are identical
    either way (the program is the same, only the copy is saved)."""
    if n == 0:
        return np.zeros((0,), np.float32)
    if len(src) == 0:
        return np.full((n,), 1.0 / n, np.float32)
    if jax.default_backend() == "cpu":
        # on the CPU fallback the jit scatter-add loses to host numpy
        # (VERDICT r4 weak #3) — same host-path policy as
        # search/vector_index.py; the device path stays the accelerator
        # path
        try:
            return _pagerank_host(np.asarray(src), np.asarray(dst), n,
                                  iters, damping)
        except ImportError:  # scipy absent: device path still correct
            pass
    return np.asarray(
        _pagerank_impl(
            dev_src if dev_src is not None
            else jnp.asarray(src, jnp.int32),
            dev_dst if dev_dst is not None
            else jnp.asarray(dst, jnp.int32),
            n, iters, damping,
        )
    )


def graph_snapshot(storage: Engine) -> Tuple[np.ndarray, np.ndarray, List[str]]:
    """Columnar edge snapshot: (src[E], dst[E], node_ids) with node ids
    densely indexed."""
    ids: List[str] = [n.id for n in storage.all_nodes()]
    index: Dict[str, int] = {nid: i for i, nid in enumerate(ids)}
    src, dst = [], []
    for e in storage.all_edges():
        si = index.get(e.start_node)
        di = index.get(e.end_node)
        if si is None or di is None:
            continue
        src.append(si)
        dst.append(di)
    return (
        np.asarray(src, dtype=np.int32),
        np.asarray(dst, dtype=np.int32),
        ids,
    )


def pagerank_engine(
    storage: Engine, iters: int = 20, damping: float = 0.85, plane=None,
) -> List[Tuple[str, float]]:
    """PageRank over the whole stored graph, scores descending.

    With ``plane`` (a query/device_graph.DeviceGraphPlane over this
    storage's catalog) the edge snapshot AND its device transfer come
    from the plane's version-keyed cache: repeat calls stop re-listing
    the store and re-shipping edge arrays. Results are bit-identical —
    the snapshot is built by the same ``graph_snapshot`` either way."""
    snap = None
    if plane is not None and plane.catalog.storage is storage:
        snap = plane.pagerank_snapshot()
    if snap is not None:
        src, dst, ids = snap["src"], snap["dst"], snap["ids"]
        scores = pagerank_arrays(src, dst, len(ids), iters, damping,
                                 dev_src=snap["dev_src"],
                                 dev_dst=snap["dev_dst"])
    else:
        src, dst, ids = graph_snapshot(storage)
        scores = pagerank_arrays(src, dst, len(ids), iters, damping)
    order = np.argsort(-scores)
    return [(ids[i], float(scores[i])) for i in order]


@functools.partial(jax.jit, static_argnames=("n",))
def degree_counts(src: jnp.ndarray, dst: jnp.ndarray, n: int):
    """(out_degree[n], in_degree[n]) in one fused pass."""
    out_d = jnp.zeros((n,), jnp.int32).at[src].add(1)
    in_d = jnp.zeros((n,), jnp.int32).at[dst].add(1)
    return out_d, in_d
