"""Pallas TPU kernel: fused cosine-similarity + blockwise top-k.

Replaces the two-pass device path (matmul → materialize [B,C] scores in
HBM → top_k) with a single fused kernel that never writes the score
matrix back to HBM. The reference fuses the same way in its Metal path
(shaders_darwin.metal topk_select over cosine_similarity_normalized
outputs, 43-360) and CUDA path (cuda_kernels.cu:263-420); on TPU the
equivalent is one Pallas kernel that

- streams [BLOCK_C, D] tiles of the embedding matrix HBM→VMEM via the
  grid pipeline,
- computes the [B, BLOCK_C] score tile on the MXU,
- applies the validity mask (capacity-padded buffers, SURVEY.md §7
  "dynamic shapes"), and
- reduces the tile to [B, KPAD] block-local winners in VMEM,

leaving only an [nblocks*KPAD]-wide final top-k for XLA — O(C/BLOCK_C·K)
HBM traffic instead of O(C).

Two-stage (block-local winners → global merge) is the standard TPU
top-k decomposition; exactness holds because the global top-k of the
union of block top-k's equals the full top-k whenever k <= KPAD.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

NEG_INF = -1e30
_KPAD = 128  # lane-aligned per-block winner count (k <= _KPAD)
_BLOCK_C = 1024  # matrix rows per grid step (4 MB VMEM tile at D=1024)


def _block_topk_kernel(q_ref, m_ref, mask_ref, s_out_ref, i_out_ref, *, k: int):
    """One grid step: score a [BLOCK_C, D] tile against all queries and
    keep the tile's top-k per query row."""
    import jax.experimental.pallas as pl

    step = pl.program_id(0)
    block_c = m_ref.shape[0]

    # [B, BLOCK_C] scores on the MXU; inputs are pre-normalized so
    # cosine == dot.
    scores = jax.lax.dot_general(
        q_ref[:], m_ref[:],
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    # mask block is [BLOCK_C] float {0,1} (1-D: lane tiling only, no
    # sublane constraint — a [1, BLOCK_C] 2-D block violates the TPU's
    # (8, 128) tiling requirement); invalid -> NEG_INF
    scores = scores + (mask_ref[:][None, :] - 1.0) * 1e30

    col = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
    base = step * block_c

    s_cols = []
    i_cols = []
    for _ in range(k):
        m = jnp.max(scores, axis=1)  # [B]
        is_max = scores == m[:, None]
        idx = jnp.min(jnp.where(is_max, col, block_c), axis=1)  # [B]
        s_cols.append(m)
        i_cols.append(base + idx)
        scores = jnp.where(col == idx[:, None], NEG_INF, scores)

    b = scores.shape[0]
    fill_s = jnp.full((b, _KPAD - k), NEG_INF, dtype=jnp.float32)
    fill_i = jnp.zeros((b, _KPAD - k), dtype=jnp.int32)
    s_out_ref[0] = jnp.concatenate(
        [jnp.stack(s_cols, axis=1), fill_s], axis=1
    )
    i_out_ref[0] = jnp.concatenate(
        [jnp.stack(i_cols, axis=1).astype(jnp.int32), fill_i], axis=1
    )


@functools.partial(
    jax.jit, static_argnames=("k", "block_c", "interpret")
)
def _fused_cosine_topk_impl(
    queries: jnp.ndarray,  # [B, D] normalized, B % 8 == 0
    matrix: jnp.ndarray,  # [C, D] normalized, C % block_c == 0
    maskf: jnp.ndarray,  # [C] float32 {0,1}
    k: int,
    block_c: int,
    interpret: bool,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, d = queries.shape
    c = matrix.shape[0]
    nblocks = c // block_c

    kernel = functools.partial(_block_topk_kernel, k=k)
    out_shape = (
        jax.ShapeDtypeStruct((nblocks, b, _KPAD), jnp.float32),
        jax.ShapeDtypeStruct((nblocks, b, _KPAD), jnp.int32),
    )
    grid_spec = pl.GridSpec(
        grid=(nblocks,),
        in_specs=[
            pl.BlockSpec((b, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec(
                (block_c, d), lambda i: (i, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (block_c,), lambda i: (i,), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=(
            pl.BlockSpec(
                (1, b, _KPAD), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (1, b, _KPAD), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
            ),
        ),
    )
    block_s, block_i = pl.pallas_call(
        kernel,
        out_shape=out_shape,
        grid_spec=grid_spec,
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=2 * b * c * d,
            bytes_accessed=c * d * 4 + b * d * 4 + nblocks * b * _KPAD * 8,
            transcendentals=0,
        ),
    )(queries, matrix, maskf)

    # global merge: [B, nblocks*KPAD] -> top-k (pad lanes hold NEG_INF)
    all_s = jnp.transpose(block_s, (1, 0, 2)).reshape(b, nblocks * _KPAD)
    all_i = jnp.transpose(block_i, (1, 0, 2)).reshape(b, nblocks * _KPAD)
    top_s, pos = jax.lax.top_k(all_s, k)
    top_i = jnp.take_along_axis(all_i, pos, axis=1)
    return top_s, top_i


def fused_cosine_topk(
    queries: jnp.ndarray,
    matrix: jnp.ndarray,
    valid: jnp.ndarray,
    k: int,
    *,
    interpret: bool | None = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Fused exact cosine top-k (Pallas). Same contract as
    ops.similarity.cosine_topk: inputs L2-normalized, returns
    (scores [B,k], indices [B,k]).

    Falls back to the XLA implementation (with the same dense/chunked
    HBM routing as the vector index) when shapes don't meet the kernel's
    tiling constraints (D % 128, C % block, k <= 128, B <= 256), or when
    not running on a TPU backend — interpret-mode emulation is for tests
    only and must be requested explicitly.
    """
    from nornicdb_tpu.ops.similarity import cosine_topk_auto

    b, d = queries.shape
    c = matrix.shape[0]
    k_eff = min(k, c)
    block_c = min(_BLOCK_C, c)
    if interpret is None and jax.default_backend() != "tpu":
        return cosine_topk_auto(queries, matrix, valid, k)
    if (
        d % 128 != 0
        or c % block_c != 0
        or k_eff > _KPAD
        or k_eff < 1
        or b > 256  # VMEM bound: queries + score tile must fit
    ):
        return cosine_topk_auto(queries, matrix, valid, k)
    if interpret is None:
        interpret = False

    b_pad = max(8, -(-b // 8) * 8)
    if b_pad != b:
        queries = jnp.pad(queries, ((0, b_pad - b), (0, 0)))
    maskf = valid.astype(jnp.float32)
    s, idx = _fused_cosine_topk_impl(
        queries, matrix, maskf, k_eff, block_c, interpret
    )
    return s[:b], idx[:b]
