"""Vectorized decay scoring: one device pass over the columnar
access/age/importance state replaces N per-node ``score()`` calls.

Reference semantics: nornicdb_tpu/decay.py (pkg/decay lineage). Per
node the host computes ``recency = 0.5^(age/half_life)``,
``frequency = 1 - exp(-accesses/10)``, a weighted sum with the
importance prior, then a scalar Kalman update
(nornicdb_tpu/filters.py). All of it is elementwise, so the whole
sweep is one fused program; the Kalman recurrence is replicated here
EXACTLY (same branch structure, same constants) so a device sweep and
a host sweep walk the same state machine — only f32-vs-f64 rounding
differs, which the caller resolves by re-scoring the verdict-boundary
band in f64 on the host (background/device_plane.py).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


@functools.lru_cache(maxsize=8)
def _decay_fn(w_recency: float, w_frequency: float, w_importance: float,
              q: float, r: float):
    """Compiled sweep for one weight/noise configuration (the manager's
    weights are fixed at construction, so this caches one program)."""

    @jax.jit
    def run(age_ms: jnp.ndarray,       # [m] f32
            half_life: jnp.ndarray,    # [m] f32
            accesses: jnp.ndarray,     # [m] f32
            importance: jnp.ndarray,   # [m] f32
            est: jnp.ndarray,          # [m] f32 Kalman estimate
            err: jnp.ndarray,          # [m] f32 Kalman error
            init: jnp.ndarray):        # [m] bool Kalman initialized
        recency = jnp.exp2(-age_ms / half_life)
        frequency = 1.0 - jnp.exp(-accesses / 10.0)
        raw = (w_recency * recency + w_frequency * frequency
               + w_importance * importance)
        # KalmanFilter.update, elementwise (filters.py:update)
        err1 = err + q
        gain = err1 / (err1 + r)
        est_u = est + gain * (raw - est)
        err_u = err1 * (1.0 - gain)
        score = jnp.where(init, est_u, raw)
        new_est = jnp.where(init, est_u, raw)
        new_err = jnp.where(init, err_u, err)
        return score, new_est, new_err

    return run


def decay_scores(
    age_ms: np.ndarray, half_life: np.ndarray, accesses: np.ndarray,
    importance: np.ndarray, est: np.ndarray, err: np.ndarray,
    init: np.ndarray, weights: Tuple[float, float, float],
    process_noise: float, measurement_noise: float,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """One dispatch over the whole sweep's columns; returns (score,
    new_kalman_estimate, new_kalman_error) as host f32 arrays."""
    fn = _decay_fn(float(weights[0]), float(weights[1]),
                   float(weights[2]), float(process_noise),
                   float(measurement_noise))
    s, e, v = fn(jnp.asarray(age_ms, jnp.float32),
                 jnp.asarray(half_life, jnp.float32),
                 jnp.asarray(accesses, jnp.float32),
                 jnp.asarray(importance, jnp.float32),
                 jnp.asarray(est, jnp.float32),
                 jnp.asarray(err, jnp.float32),
                 jnp.asarray(init))
    return np.asarray(s), np.asarray(e), np.asarray(v)


def decay_score_host_f64(age_ms: float, half_life: float,
                         accesses: float, importance: float,
                         est: float, err: float, init: bool,
                         weights: Tuple[float, float, float],
                         q: float, r: float) -> float:
    """f64 reference for ONE node from the same pre-sweep state — the
    device plane's boundary-band re-check. Pure: does not advance any
    live KalmanFilter (decay.score() would mutate it a second time)."""
    import math

    recency = math.pow(0.5, age_ms / half_life)
    frequency = 1.0 - math.exp(-accesses / 10.0)
    raw = (weights[0] * recency + weights[1] * frequency
           + weights[2] * importance)
    if not init:
        return raw
    err1 = err + q
    gain = err1 / (err1 + r)
    return est + gain * (raw - est)
