"""Cosine similarity + top-k kernels — the hot path of vector search.

Replaces the reference's per-backend kernels (CUDA cuda_kernels.cu:263-420
cosine/topk, Metal shaders_darwin.metal:43-360, Vulkan shaders/*.comp,
pkg/simd BatchCosineSimilarity simd.go:149) with jitted XLA:

- one [B,D] x [D,C] matmul lands on the MXU;
- capacity-padded buffers + validity masks keep shapes static so XLA
  never recompiles as the index grows (SURVEY.md §7 "dynamic shapes");
- a chunked lax.scan variant bounds HBM for very large C by never
  materializing the full [B,C] score matrix.

All functions are pure and jit-cached per (shape, k) signature.
"""

from __future__ import annotations

import functools
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def pad_dim(n: int, minimum: int = 256) -> int:
    """Round capacity up to the next power-of-two multiple of `minimum`
    (a lane-friendly size) so jit caches stay small as the index grows."""
    if n <= minimum:
        return minimum
    capacity = minimum
    while capacity < n:
        capacity *= 2
    return capacity


@jax.jit
def l2_normalize(x: jnp.ndarray, eps: float = 1e-12) -> jnp.ndarray:
    """Row-normalize so cosine similarity reduces to a dot product
    (reference: normalize kernels, cuda_kernels.cu:206)."""
    norm = jnp.sqrt(jnp.sum(x * x, axis=-1, keepdims=True))
    return x / jnp.maximum(norm, eps)


@functools.partial(jax.jit, static_argnames=("k",))
def _cosine_topk_impl(
    queries: jnp.ndarray,  # [B, D] (normalized)
    matrix: jnp.ndarray,  # [C, D] (normalized, capacity-padded)
    valid: jnp.ndarray,  # [C] bool
    k: int,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    scores = queries @ matrix.T  # [B, C] — MXU
    scores = jnp.where(valid[None, :], scores, NEG_INF)
    return jax.lax.top_k(scores, k)


def cosine_topk(
    queries: jnp.ndarray,
    matrix: jnp.ndarray,
    valid: jnp.ndarray,
    k: int,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Exact cosine top-k. Inputs must be L2-normalized. Returns
    (scores [B,k], indices [B,k]); masked-out rows score NEG_INF."""
    k = min(k, matrix.shape[0])
    return _cosine_topk_impl(queries, matrix, valid, k)


@functools.partial(jax.jit, static_argnames=("k", "chunk"))
def _cosine_topk_chunked_impl(
    queries: jnp.ndarray,
    matrix: jnp.ndarray,
    valid: jnp.ndarray,
    k: int,
    chunk: int,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    b = queries.shape[0]
    c = matrix.shape[0]
    n_chunks = c // chunk  # capacity is a multiple of chunk by construction

    def step(carry, i):
        best_s, best_i = carry
        rows = jax.lax.dynamic_slice_in_dim(matrix, i * chunk, chunk, axis=0)
        vmask = jax.lax.dynamic_slice_in_dim(valid, i * chunk, chunk, axis=0)
        s = queries @ rows.T  # [B, chunk]
        s = jnp.where(vmask[None, :], s, NEG_INF)
        idx = i * chunk + jnp.arange(chunk, dtype=jnp.int32)
        cat_s = jnp.concatenate([best_s, s], axis=1)
        cat_i = jnp.concatenate([best_i, jnp.broadcast_to(idx, (b, chunk))], axis=1)
        top_s, pos = jax.lax.top_k(cat_s, k)
        top_i = jnp.take_along_axis(cat_i, pos, axis=1)
        return (top_s, top_i), None

    init = (
        jnp.full((b, k), NEG_INF, dtype=queries.dtype),
        jnp.zeros((b, k), dtype=jnp.int32),
    )
    (best_s, best_i), _ = jax.lax.scan(
        step, init, jnp.arange(n_chunks, dtype=jnp.int32)
    )
    return best_s, best_i


# above this row count, route to the chunked kernel to bound HBM
CHUNKED_THRESHOLD = 262_144


def cosine_topk_auto(
    queries: jnp.ndarray,
    matrix: jnp.ndarray,
    valid: jnp.ndarray,
    k: int,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Dense below CHUNKED_THRESHOLD rows, chunked above — the single
    routing point so every caller (and every fallback) bounds HBM the
    same way."""
    if matrix.shape[0] > CHUNKED_THRESHOLD:
        return cosine_topk_chunked(queries, matrix, valid, k)
    return cosine_topk(queries, matrix, valid, k)


def cosine_topk_chunked(
    queries: jnp.ndarray,
    matrix: jnp.ndarray,
    valid: jnp.ndarray,
    k: int,
    chunk: int = 16384,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Exact cosine top-k without materializing the [B,C] score matrix:
    scans C in chunks, keeping a running [B,k] best set. Use when
    B*C*4 bytes would pressure HBM (e.g. C ~ 1M)."""
    c = matrix.shape[0]
    k = min(k, c)
    if c <= chunk:
        return _cosine_topk_impl(queries, matrix, valid, k)
    chunk = min(chunk, c)
    # pad_dim capacities are power-of-two multiples of 256, so a power-of-two
    # chunk divides them; for other capacities fall back to dense rather
    # than degrading to a tiny-chunk scan
    while c % chunk != 0 and chunk >= 512:
        chunk //= 2
    if c % chunk != 0:
        return _cosine_topk_impl(queries, matrix, valid, k)
    return _cosine_topk_chunked_impl(queries, matrix, valid, k, chunk)


def concat_topk(
    scores_parts: Sequence[jnp.ndarray],
    ids_parts: Sequence[jnp.ndarray],
    k: int,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Merge per-shard top-k blocks: concatenate [B, k_i] score/id parts
    in shard order and take one global top-k. This is the single-device
    reference of the ``all_gather + top_k`` collective merge — the
    shard-major concat layout is identical to a tiled all-gather, so the
    merged ranking (including tie order, which lax.top_k resolves by
    lower concatenated position) is bit-identical to the sharded path.
    Shared by the CAGRA walk, the device BM25 scorer and the fused
    hybrid pipeline."""
    all_s = jnp.concatenate(list(scores_parts), axis=1)
    all_i = jnp.concatenate(list(ids_parts), axis=1)
    top_s, pos = jax.lax.top_k(all_s, k)
    return top_s, jnp.take_along_axis(all_i, pos, axis=1)


@functools.partial(jax.jit, static_argnames=("k",))
def euclidean_topk(
    queries: jnp.ndarray,
    matrix: jnp.ndarray,
    valid: jnp.ndarray,
    k: int,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Top-k by (negated) squared euclidean distance
    (reference: euclidean_distance kernel, shaders_darwin.metal)."""
    q2 = jnp.sum(queries * queries, axis=1, keepdims=True)  # [B,1]
    m2 = jnp.sum(matrix * matrix, axis=1)  # [C]
    d2 = q2 + m2[None, :] - 2.0 * (queries @ matrix.T)
    d2 = jnp.where(valid[None, :], -d2, NEG_INF)
    neg_d, idx = jax.lax.top_k(d2, k)
    return -neg_d, idx


@jax.jit
def batch_dot(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Row-wise dot products (reference: batch_dot kernel)."""
    return jnp.sum(a * b, axis=-1)


@functools.partial(jax.jit, static_argnames=("threshold_is_min",))
def filter_by_similarity(
    query: jnp.ndarray,  # [D]
    matrix: jnp.ndarray,  # [C, D]
    valid: jnp.ndarray,  # [C]
    threshold: float,
    threshold_is_min: bool = True,
) -> jnp.ndarray:
    """Boolean mask of rows whose cosine similarity clears the threshold
    (reference: filter_by_similarity kernel, shaders_darwin.metal)."""
    scores = matrix @ query
    ok = scores >= threshold if threshold_is_min else scores <= threshold
    return ok & valid
