"""Pallas TPU kernel: blockwise (flash) attention with online softmax.

The single-chip complement of ring attention (parallel/ring_attention.py
covers the sequence-sharded case): instead of materializing the [S, S]
logit matrix in HBM, key/value tiles stream HBM->VMEM through the grid
pipeline and the softmax is accumulated online per query block —

    for each KV tile:
        s     = q_tile @ k_tile^T            (MXU)
        m'    = max(m, rowmax(s))
        alpha = exp(m - m')
        p     = exp(s - m')
        acc   = acc * alpha + p @ v_tile     (MXU)
        l     = l * alpha + rowsum(p)
    out = acc / l

HBM traffic drops from O(S^2) to O(S * D). The grid is
(batch*heads, q_blocks, kv_blocks) with the kv axis innermost so the
VMEM scratch accumulators carry across the kv steps of one q block.

``flash_attention`` is exact (not an approximation): outputs match the
naive softmax path to float tolerance, asserted in tests in interpret
mode and against the encoder's XLA attention.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30
_BLOCK_Q = 128
_BLOCK_K = 128


def _flash_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref,
                  acc_ref, m_ref, l_ref, *, scale: float, kv_steps: int):
    import jax.experimental.pallas as pl

    kv_step = pl.program_id(2)

    @pl.when(kv_step == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    q = q_ref[0].astype(jnp.float32)          # [BQ, D]
    k = k_ref[0].astype(jnp.float32)          # [BK, D]
    v = v_ref[0].astype(jnp.float32)          # [BK, D]
    kv_mask = mask_ref[0]                     # [1, BK] bool

    s = jax.lax.dot_general(
        q, k, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale                                  # [BQ, BK]
    s = jnp.where(kv_mask, s, NEG_INF)

    m_prev = m_ref[...]                        # [BQ, 1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new)                     # [BQ, BK]
    acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
        p, v, dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=-1,
                                              keepdims=True)
    m_ref[...] = m_new

    @pl.when(kv_step == kv_steps - 1)
    def _finalize():
        denom = jnp.maximum(l_ref[...], 1e-30)
        o_ref[0] = (acc_ref[...] / denom).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("block_q", "block_k", "interpret"))
def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mask: Optional[jnp.ndarray] = None,
    *,
    block_q: int = _BLOCK_Q,
    block_k: int = _BLOCK_K,
    interpret: bool = False,
) -> jnp.ndarray:
    """Exact attention without the [S, S] HBM matrix.

    q, k, v: [B, S, H, D_head]; mask: [B, S] bool over keys (True =
    attend). Returns [B, S, H, D_head]. Sequence lengths are padded to
    the block size internally; padded keys are masked out and padded
    query rows are dropped on return.
    """
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, s, h, d = q.shape
    if mask is None:
        mask = jnp.ones((b, s), dtype=bool)
    scale = d ** -0.5

    s_pad_q = -s % block_q
    s_pad_k = -s % block_k
    sq = s + s_pad_q
    sk = s + s_pad_k

    def pad_seq(x, pad):
        return jnp.pad(x, ((0, 0), (0, pad), (0, 0), (0, 0)))

    qp = pad_seq(q, s_pad_q)
    kp = pad_seq(k, s_pad_k)
    vp = pad_seq(v, s_pad_k)
    maskp = jnp.pad(mask, ((0, 0), (0, s_pad_k)))  # padded keys excluded

    def fold(x, sl):  # [B, S, H, D] -> [B*H, S, D]
        return x.transpose(0, 2, 1, 3).reshape(b * h, sl, d)

    qf = fold(qp, sq)
    kf = fold(kp, sk)
    vf = fold(vp, sk)
    maskf = jnp.repeat(maskp[:, None, :], h, axis=1).reshape(b * h, 1, sk)

    q_steps = sq // block_q
    kv_steps = sk // block_k

    out = pl.pallas_call(
        functools.partial(_flash_kernel, scale=scale, kv_steps=kv_steps),
        grid=(b * h, q_steps, kv_steps),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda g, i, j: (g, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda g, i, j: (g, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda g, i, j: (g, j, 0)),
            pl.BlockSpec((1, 1, block_k), lambda g, i, j: (g, 0, j)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda g, i, j: (g, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),   # acc
            pltpu.VMEM((block_q, 1), jnp.float32),   # running max
            pltpu.VMEM((block_q, 1), jnp.float32),   # running denom
        ],
        interpret=interpret,
    )(qf, kf, vf, maskf)

    out = out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
    return out[:, :s]


def reference_attention(q, k, v, mask=None):
    """Naive [S, S]-materializing softmax attention, for parity tests."""
    b, s, h, d = q.shape
    if mask is None:
        mask = jnp.ones((b, s), dtype=bool)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * (d ** -0.5)
    logits = jnp.where(mask[:, None, None, :], logits, NEG_INF)
    weights = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", weights, v.astype(jnp.float32))
    return out.astype(q.dtype)
