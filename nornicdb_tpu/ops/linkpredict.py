"""Device link prediction: masked sparse 2-hop expansion + weighted
segment reduction + top-k, one compiled program per pow2 bucket.

Reference semantics: nornicdb_tpu/linkpredict.py (pkg/cypher/
linkprediction.go lineage). A seed's candidates are its 2-hop
neighborhood; the score of pair ``(u, v)`` is ``sum_z w(z)`` over
common neighbors ``z`` — ``w`` encodes the scorer (common-neighbors:
1, Adamic–Adar: 1/ln(deg z), resource-allocation: 1/deg z). The host
loop intersects Python sets per candidate pair; here the whole batch
runs as one dispatch over a CSR snapshot:

1. gather the sorted 1-hop row of each seed (width ``f1``, sentinel
   ``n`` pads);
2. expand to the full 2-hop multiset (width ``f1*f2`` — COMPLETE
   coverage; the dispatch is refused, not truncated, when the bucket
   would overflow), carrying ``w(mid)`` per element;
3. sort by candidate id, segment the runs, and segment-sum the
   weights — one score per distinct candidate;
4. mask sentinels, the seed itself, and existing neighbors (a
   searchsorted membership probe against the sorted 1-hop row);
5. ``lax.top_k`` the masked scores.

Exactness: common-neighbors scores are small-integer sums in f32
(exact below 2^24). Weighted scorers accumulate f32 rounding, so the
caller re-scores the kept candidates exactly on the host and degrades
when an excluded candidate could reach the cut (see
background/device_plane.py).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


@functools.lru_cache(maxsize=64)
def _lp_topk_fn(f1: int, f2: int, kp: int):
    """Compiled batched link-prediction top-k for the pow2 bucket
    ``(f1, f2, kp)``: per-seed 1-hop width f1, per-mid fanout f2,
    kept candidates kp."""

    @jax.jit
    def run(seeds: jnp.ndarray,    # [b] int32, -1 pads
            indptr: jnp.ndarray,   # [n+1] int32 CSR row starts
            nbr: jnp.ndarray,      # [E] int32, sorted within each row
            w: jnp.ndarray,        # [n] f32 per-mid weight
            n: jnp.ndarray):       # () int32 sentinel / node count
        W = f1 * f2

        def one(s):
            valid_seed = s >= 0
            sc = jnp.where(valid_seed, s, 0)
            start1 = indptr[sc]
            deg1 = indptr[sc + 1] - start1
            j = jnp.arange(f1, dtype=jnp.int32)
            take1 = valid_seed & (j < deg1)
            # sorted row + sentinel pads stays sorted: row values < n
            h1 = jnp.where(take1, nbr[jnp.clip(start1 + j, 0,
                                               nbr.shape[0] - 1)], n)
            # 2-hop expansion: mid = h1[j]; every neighbor of mid is a
            # candidate scored by w[mid]
            midc = jnp.where(take1, h1, 0)
            start2 = indptr[midc]
            deg2 = indptr[midc + 1] - start2
            ll = jnp.arange(f2, dtype=jnp.int32)
            take2 = take1[:, None] & (ll[None, :] < deg2[:, None])
            flat_idx = jnp.clip(start2[:, None] + ll[None, :], 0,
                                nbr.shape[0] - 1)
            cand = jnp.where(take2, nbr[flat_idx], n).reshape(W)
            wt = jnp.where(take2, w[midc][:, None],
                           jnp.float32(0.0)).reshape(W)
            # group equal candidates: sort by id, flag run heads,
            # segment-sum the weights per run
            cand_s, wt_s = jax.lax.sort((cand, wt), num_keys=1)
            first = jnp.concatenate([
                jnp.ones((1,), bool), cand_s[1:] != cand_s[:-1]])
            run_id = jnp.cumsum(first.astype(jnp.int32)) - 1
            scores = jax.ops.segment_sum(
                wt_s, run_id, num_segments=W, indices_are_sorted=True)
            cand_of = jax.ops.segment_max(
                cand_s, run_id, num_segments=W, indices_are_sorted=True)
            n_runs = run_id[-1] + 1
            slot = jnp.arange(W, dtype=jnp.int32)
            live = slot < n_runs
            # mask sentinels, the seed, and existing 1-hop neighbors
            pos = jnp.searchsorted(h1, cand_of).astype(jnp.int32)
            in_hop1 = h1[jnp.clip(pos, 0, f1 - 1)] == cand_of
            keep = (live & (cand_of < n) & (cand_of != s)
                    & jnp.logical_not(in_hop1))
            masked = jnp.where(keep, scores, -jnp.inf)
            vals, idx = jax.lax.top_k(masked, kp)
            sel = cand_of[idx]
            distinct = jnp.sum(keep.astype(jnp.int32))
            return vals, sel, distinct

        return jax.vmap(one)(seeds)

    return run


def degree_weights(method: str, indptr: np.ndarray) -> np.ndarray:
    """Per-mid weight column for the scorer ``method``, computed on
    the host in f64 then narrowed to f32 (one column per snapshot, not
    per call). A common neighbor always has degree >= 2, so the
    Adamic–Adar log is never <= 0 where it matters; degree<=1 rows get
    weight 0 (they contribute no pairs anyway)."""
    deg = (indptr[1:] - indptr[:-1]).astype(np.float64)
    if method == "common_neighbors":
        w = np.ones_like(deg)
    elif method == "adamic_adar":
        with np.errstate(divide="ignore"):
            w = np.where(deg > 1.0, 1.0 / np.log(np.maximum(deg, 2.0)),
                         0.0)
    elif method == "resource_allocation":
        with np.errstate(divide="ignore"):
            w = np.where(deg > 0.0, 1.0 / np.maximum(deg, 1.0), 0.0)
    else:
        raise ValueError(f"unsupported device scorer: {method}")
    return w.astype(np.float32)


def linkpredict_topk(
    seeds: np.ndarray,      # [b] int32 (-1 pads allowed)
    indptr,                 # device or host [n+1] int32
    nbr,                    # device or host [E] int32 (row-sorted)
    w,                      # device or host [n] f32
    n: int,
    f1: int, f2: int, kp: int,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """One dispatch: per-seed top-``kp`` (scores, candidates) plus the
    exact distinct-candidate count (the caller's coverage guard)."""
    fn = _lp_topk_fn(f1, f2, kp)
    vals, sel, distinct = fn(
        jnp.asarray(seeds, jnp.int32), indptr, nbr, w,
        jnp.int32(n))
    return np.asarray(vals), np.asarray(sel), np.asarray(distinct)
