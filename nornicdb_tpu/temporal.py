"""Temporal access tracking: velocity, sessions, co-access patterns.

Reference: pkg/temporal — Tracker (tracker.go:216), RecordAccess (:419),
session detection, pattern detector, relationship evolution (3,347 LoC).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Tuple

from nornicdb_tpu.filters import VelocityKalmanFilter

SESSION_GAP_S = 1800.0  # 30 min of silence ends a session
CO_ACCESS_WINDOW_S = 300.0  # accesses within 5 min are "together"


@dataclass
class AccessRecord:
    node_id: str
    at: float


@dataclass
class NodeAccessStats:
    count: int = 0
    first_at: float = 0.0
    last_at: float = 0.0
    velocity: float = 0.0  # accesses/hour trend (Kalman-smoothed)


class TemporalTracker:
    def __init__(self, history_limit: int = 10_000):
        self._lock = threading.Lock()
        self._history: Deque[AccessRecord] = deque(maxlen=history_limit)
        self._stats: Dict[str, NodeAccessStats] = {}
        self._filters: Dict[str, VelocityKalmanFilter] = {}
        self._session_id = 0
        self._session_start: Optional[float] = None
        self._session_last: Optional[float] = None
        self._session_nodes: List[str] = []
        self._session_times: List[Tuple[str, float]] = []
        # integrated sub-trackers (reference: the Tracker owns pattern
        # detection and relationship evolution, tracker.go:216)
        self.patterns = PatternDetector()
        self.evolution = RelationshipEvolution()

    # -- recording ---------------------------------------------------------

    def record_access(self, node_id: str, at: Optional[float] = None) -> None:
        at = at if at is not None else time.time()
        with self._lock:
            self._history.append(AccessRecord(node_id, at))
            st = self._stats.setdefault(node_id, NodeAccessStats(first_at=at))
            st.count += 1
            st.last_at = at
            kf = self._filters.setdefault(node_id, VelocityKalmanFilter())
            _, vel = kf.update(float(st.count), at)
            st.velocity = vel * 3600.0  # per hour
            # session tracking
            if self._session_last is None or at - self._session_last > SESSION_GAP_S:
                self._session_id += 1
                self._session_start = at
                self._session_nodes = []
            self._session_last = at
            # only accesses inside the co-access window count as
            # "together" (CO_ACCESS_WINDOW_S — same definition as
            # co_accessed()); session membership alone can span hours
            recent = {
                n for n, t in self._session_times[-8:]
                if n != node_id and at - t <= CO_ACCESS_WINDOW_S
            }
            self._session_nodes.append(node_id)
            self._session_times.append((node_id, at))
            del self._session_times[:-8]
        # feed the integrated sub-trackers outside the main lock (they
        # lock themselves): access histogram + co-access edge strengths
        self.patterns.record_access(node_id, at)
        for other in recent:
            self.evolution.record_co_access(node_id, other, at=at)

    def detect_patterns(self, node_id: str,
                        now: Optional[float] = None) -> List["DetectedPattern"]:
        """Patterns for a node, fed with its current Kalman velocity.
        Pass ``now`` when analyzing replayed/historical timestamps so
        burst detection judges against the data's clock."""
        st = self.stats(node_id)
        vel = st.velocity if st else 0.0
        return self.patterns.detect_patterns(node_id, velocity=vel, now=now)

    # -- queries -----------------------------------------------------------

    def stats(self, node_id: str) -> Optional[NodeAccessStats]:
        with self._lock:
            st = self._stats.get(node_id)
            return NodeAccessStats(**vars(st)) if st else None

    @property
    def session(self) -> Tuple[int, List[str]]:
        with self._lock:
            return self._session_id, list(self._session_nodes)

    def co_accessed(
        self, node_id: str, window_s: float = CO_ACCESS_WINDOW_S
    ) -> List[Tuple[str, int]]:
        """Nodes accessed within ``window_s`` of any access to ``node_id``,
        with co-occurrence counts (feeds inference co-access suggestions)."""
        with self._lock:
            times = [r.at for r in self._history if r.node_id == node_id]
            if not times:
                return []
            counts: Dict[str, int] = {}
            for r in self._history:
                if r.node_id == node_id:
                    continue
                if any(abs(r.at - t) <= window_s for t in times):
                    counts[r.node_id] = counts.get(r.node_id, 0) + 1
            return sorted(counts.items(), key=lambda kv: -kv[1])

    def hot_nodes(self, limit: int = 10) -> List[Tuple[str, float]]:
        """Highest access-velocity nodes."""
        with self._lock:
            ranked = sorted(
                ((nid, st.velocity) for nid, st in self._stats.items()),
                key=lambda kv: -kv[1],
            )
            return ranked[:limit]


# -- pattern detection ----------------------------------------------------


@dataclass
class DetectedPattern:
    """One detected access pattern (reference: pattern_detector.go:39-59;
    types none/daily/weekly/burst/decaying/growing)."""

    type: str
    confidence: float
    peak_hour: Optional[int] = None
    peak_day: Optional[int] = None


class PatternDetector:
    """Periodic/burst/trend pattern detection over per-node access
    histograms (reference: pkg/temporal/pattern_detector.go:99-392).

    Daily/weekly periodicity is judged by concentration of accesses in
    hour-of-day / day-of-week histograms; bursts by the share of recent
    accesses in a short trailing window; growing/decaying by the
    Kalman-filtered access velocity."""

    def __init__(self, min_accesses: int = 6, history_limit: int = 512,
                 daily_threshold: float = 0.35, weekly_threshold: float = 0.4,
                 burst_window_s: float = 3600.0, burst_share: float = 0.5,
                 trend_velocity: float = 0.01):
        self.min_accesses = min_accesses
        self.history_limit = history_limit
        self.daily_threshold = daily_threshold
        self.weekly_threshold = weekly_threshold
        self.burst_window_s = burst_window_s
        self.burst_share = burst_share
        self.trend_velocity = trend_velocity
        self._times: Dict[str, Deque[float]] = {}
        self._lock = threading.Lock()

    def record_access(self, node_id: str, at: Optional[float] = None) -> None:
        at = time.time() if at is None else at
        with self._lock:
            dq = self._times.get(node_id)
            if dq is None:
                dq = deque(maxlen=self.history_limit)
                self._times[node_id] = dq
            dq.append(at)

    def detect_patterns(self, node_id: str,
                        velocity: float = 0.0,
                        now: Optional[float] = None) -> List[DetectedPattern]:
        import math

        now = time.time() if now is None else now
        with self._lock:
            times = list(self._times.get(node_id, ()))
        out: List[DetectedPattern] = []
        if len(times) >= self.min_accesses:
            hours = [int((t % 86400) // 3600) for t in times]
            hour_hist = [0] * 24
            for h in hours:
                hour_hist[h] += 1
            # concentration in the best 3 contiguous hours; the reported
            # peak is the histogram argmax (a window tie would otherwise
            # shift the center off the true peak hour)
            best3 = 0
            for h in range(24):
                c = sum(hour_hist[(h + i) % 24] for i in range(3))
                if c > best3:
                    best3 = c
            peak_hour = hour_hist.index(max(hour_hist))
            daily_conc = best3 / len(times)
            # require spread over >= 3 distinct days, else "daily" is
            # just one busy afternoon
            days_spanned = (max(times) - min(times)) / 86400.0
            if daily_conc >= self.daily_threshold and days_spanned >= 2.0:
                out.append(DetectedPattern(
                    "daily", confidence=round(min(daily_conc, 1.0), 3),
                    peak_hour=peak_hour))
            dows = [int((t // 86400 + 4) % 7) for t in times]  # epoch day 0 = Thu
            dow_hist = [0] * 7
            for d in dows:
                dow_hist[d] += 1
            weekly_conc = max(dow_hist) / len(times)
            if weekly_conc >= self.weekly_threshold and days_spanned >= 13.0:
                out.append(DetectedPattern(
                    "weekly", confidence=round(min(weekly_conc, 1.0), 3),
                    peak_day=int(dow_hist.index(max(dow_hist)))))
            recent = sum(1 for t in times if now - t <= self.burst_window_s)
            if recent >= self.min_accesses and (
                recent / len(times) >= self.burst_share
            ):
                out.append(DetectedPattern(
                    "burst", confidence=round(recent / len(times), 3)))
        if velocity > self.trend_velocity:
            out.append(DetectedPattern(
                "growing", confidence=min(1.0, velocity / (10 * self.trend_velocity))))
        elif velocity < -self.trend_velocity:
            out.append(DetectedPattern(
                "decaying", confidence=min(1.0, -velocity / (10 * self.trend_velocity))))
        return out

    def has_pattern(self, node_id: str, pattern_type: str,
                    velocity: float = 0.0) -> bool:
        return any(p.type == pattern_type
                   for p in self.detect_patterns(node_id, velocity))

    def peak_access_time(self, node_id: str) -> Tuple[int, int, float]:
        """(hour, day_of_week, confidence) of the busiest slot
        (reference: GetPeakAccessTime pattern_detector.go:344)."""
        with self._lock:
            times = list(self._times.get(node_id, ()))
        if not times:
            return 0, 0, 0.0
        hour_hist = [0] * 24
        dow_hist = [0] * 7
        for t in times:
            hour_hist[int((t % 86400) // 3600)] += 1
            dow_hist[int((t // 86400 + 4) % 7)] += 1
        hour = hour_hist.index(max(hour_hist))
        day = dow_hist.index(max(dow_hist))
        conf = (max(hour_hist) / len(times) + max(dow_hist) / len(times)) / 2
        return hour, day, round(conf, 3)

    def reset_node(self, node_id: str) -> None:
        with self._lock:
            self._times.pop(node_id, None)

    def reset(self) -> None:
        with self._lock:
            self._times.clear()


# -- relationship evolution -----------------------------------------------


@dataclass
class RelationshipTrend:
    """(reference: relationship_evolution.go:78-100)"""

    source_id: str
    target_id: str
    current_strength: float
    velocity: float
    predicted_strength: float  # 5 steps ahead
    trend: str  # strengthening | weakening | stable


class RelationshipEvolution:
    """Kalman-filtered edge strength tracking (reference:
    pkg/temporal/relationship_evolution.go:145-430). Each co-access
    bumps an edge's strength measurement; the velocity filter smooths it
    and exposes whether the relationship is strengthening, weakening,
    emerging, or prunable."""

    def __init__(self, strengthen_threshold: float = 0.01,
                 weaken_threshold: float = -0.01,
                 emerging_max_age_s: float = 7 * 86400.0,
                 decay_per_day: float = 0.02,
                 max_edges: int = 50_000):
        self.strengthen_threshold = strengthen_threshold
        self.weaken_threshold = weaken_threshold
        self.emerging_max_age_s = emerging_max_age_s
        self.decay_per_day = decay_per_day
        self.max_edges = max_edges
        self._edges: Dict[Tuple[str, str], Dict] = {}
        self._lock = threading.Lock()

    def _evict_locked(self) -> None:
        """Bound per-pair state: on overflow drop the least-recently
        bumped 10% (the tracker feeds this from the access hot path, so
        unbounded growth would be O(accessed-pairs) memory)."""
        if len(self._edges) < self.max_edges:
            return
        by_age = sorted(self._edges.items(), key=lambda kv: kv[1]["last_at"])
        for k, _ in by_age[: max(1, self.max_edges // 10)]:
            del self._edges[k]

    @staticmethod
    def _key(a: str, b: str) -> Tuple[str, str]:
        return (a, b) if a <= b else (b, a)

    def record_co_access(self, source_id: str, target_id: str,
                         weight: float = 1.0,
                         at: Optional[float] = None) -> None:
        at = time.time() if at is None else at
        key = self._key(source_id, target_id)
        with self._lock:
            tr = self._edges.get(key)
            if tr is None:
                self._evict_locked()
                tr = {"filter": VelocityKalmanFilter(), "raw": 0.0,
                      "first_at": at, "last_at": at}
                self._edges[key] = tr
            # decay the raw strength for the silence since last access
            silent_days = max(0.0, (at - tr["last_at"]) / 86400.0)
            tr["raw"] = max(0.0, tr["raw"] - self.decay_per_day * silent_days)
            tr["raw"] += weight
            tr["last_at"] = at
            tr["filter"].update(tr["raw"], at)

    def update_weight(self, source_id: str, target_id: str,
                      new_weight: float, at: Optional[float] = None) -> None:
        at = time.time() if at is None else at
        key = self._key(source_id, target_id)
        with self._lock:
            tr = self._edges.get(key)
            if tr is None:
                self._evict_locked()
                tr = {"filter": VelocityKalmanFilter(), "raw": new_weight,
                      "first_at": at, "last_at": at}
                self._edges[key] = tr
            tr["raw"] = new_weight
            tr["last_at"] = at
            tr["filter"].update(new_weight, at)

    def _trend_locked(self, key: Tuple[str, str]) -> Optional[RelationshipTrend]:
        tr = self._edges.get(key)
        if tr is None:
            return None
        f = tr["filter"]
        vel = f.vel
        if vel > self.strengthen_threshold:
            label = "strengthening"
        elif vel < self.weaken_threshold:
            label = "weakening"
        else:
            label = "stable"
        return RelationshipTrend(
            source_id=key[0], target_id=key[1],
            current_strength=round(f.pos, 6), velocity=round(vel, 6),
            predicted_strength=round(max(0.0, f.pos + 5 * vel), 6),
            trend=label,
        )

    def get_trend(self, source_id: str, target_id: str) -> Optional[RelationshipTrend]:
        with self._lock:
            return self._trend_locked(self._key(source_id, target_id))

    def predict_strength(self, source_id: str, target_id: str,
                         steps: int = 5) -> float:
        with self._lock:
            tr = self._edges.get(self._key(source_id, target_id))
            if tr is None:
                return 0.0
            f = tr["filter"]
            return max(0.0, f.pos + steps * f.vel)

    def _ranked(self, predicate) -> List[RelationshipTrend]:
        with self._lock:
            trends = [self._trend_locked(k) for k in self._edges]
        return [t for t in trends if t is not None and predicate(t)]

    def strengthening(self, limit: int = 10) -> List[RelationshipTrend]:
        out = self._ranked(lambda t: t.trend == "strengthening")
        out.sort(key=lambda t: -t.velocity)
        return out[:limit]

    def weakening(self, limit: int = 10) -> List[RelationshipTrend]:
        out = self._ranked(lambda t: t.trend == "weakening")
        out.sort(key=lambda t: t.velocity)
        return out[:limit]

    def emerging(self, limit: int = 10,
                 now: Optional[float] = None) -> List[RelationshipTrend]:
        """Young relationships that are strengthening
        (reference: GetEmergingRelationships :368)."""
        now = time.time() if now is None else now
        with self._lock:
            young = [
                k for k, tr in self._edges.items()
                if now - tr["first_at"] <= self.emerging_max_age_s
            ]
            trends = [self._trend_locked(k) for k in young]
        out = [t for t in trends if t is not None and t.velocity > 0]
        out.sort(key=lambda t: -t.velocity)
        return out[:limit]

    def should_prune(self, source_id: str, target_id: str,
                     threshold: float = 0.1) -> bool:
        with self._lock:
            tr = self._edges.get(self._key(source_id, target_id))
            if tr is None:
                return True
            f = tr["filter"]
        return f.pos < threshold and f.vel <= 0
