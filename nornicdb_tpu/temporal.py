"""Temporal access tracking: velocity, sessions, co-access patterns.

Reference: pkg/temporal — Tracker (tracker.go:216), RecordAccess (:419),
session detection, pattern detector, relationship evolution (3,347 LoC).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Tuple

from nornicdb_tpu.filters import VelocityKalmanFilter

SESSION_GAP_S = 1800.0  # 30 min of silence ends a session
CO_ACCESS_WINDOW_S = 300.0  # accesses within 5 min are "together"


@dataclass
class AccessRecord:
    node_id: str
    at: float


@dataclass
class NodeAccessStats:
    count: int = 0
    first_at: float = 0.0
    last_at: float = 0.0
    velocity: float = 0.0  # accesses/hour trend (Kalman-smoothed)


class TemporalTracker:
    def __init__(self, history_limit: int = 10_000):
        self._lock = threading.Lock()
        self._history: Deque[AccessRecord] = deque(maxlen=history_limit)
        self._stats: Dict[str, NodeAccessStats] = {}
        self._filters: Dict[str, VelocityKalmanFilter] = {}
        self._session_id = 0
        self._session_start: Optional[float] = None
        self._session_last: Optional[float] = None
        self._session_nodes: List[str] = []

    # -- recording ---------------------------------------------------------

    def record_access(self, node_id: str, at: Optional[float] = None) -> None:
        at = at if at is not None else time.time()
        with self._lock:
            self._history.append(AccessRecord(node_id, at))
            st = self._stats.setdefault(node_id, NodeAccessStats(first_at=at))
            st.count += 1
            st.last_at = at
            kf = self._filters.setdefault(node_id, VelocityKalmanFilter())
            _, vel = kf.update(float(st.count), at)
            st.velocity = vel * 3600.0  # per hour
            # session tracking
            if self._session_last is None or at - self._session_last > SESSION_GAP_S:
                self._session_id += 1
                self._session_start = at
                self._session_nodes = []
            self._session_last = at
            self._session_nodes.append(node_id)

    # -- queries -----------------------------------------------------------

    def stats(self, node_id: str) -> Optional[NodeAccessStats]:
        with self._lock:
            st = self._stats.get(node_id)
            return NodeAccessStats(**vars(st)) if st else None

    @property
    def session(self) -> Tuple[int, List[str]]:
        with self._lock:
            return self._session_id, list(self._session_nodes)

    def co_accessed(
        self, node_id: str, window_s: float = CO_ACCESS_WINDOW_S
    ) -> List[Tuple[str, int]]:
        """Nodes accessed within ``window_s`` of any access to ``node_id``,
        with co-occurrence counts (feeds inference co-access suggestions)."""
        with self._lock:
            times = [r.at for r in self._history if r.node_id == node_id]
            if not times:
                return []
            counts: Dict[str, int] = {}
            for r in self._history:
                if r.node_id == node_id:
                    continue
                if any(abs(r.at - t) <= window_s for t in times):
                    counts[r.node_id] = counts.get(r.node_id, 0) + 1
            return sorted(counts.items(), key=lambda kv: -kv[1])

    def hot_nodes(self, limit: int = 10) -> List[Tuple[str, float]]:
        """Highest access-velocity nodes."""
        with self._lock:
            ranked = sorted(
                ((nid, st.velocity) for nid, st in self._stats.items()),
                key=lambda kv: -kv[1],
            )
            return ranked[:limit]
