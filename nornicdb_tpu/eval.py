"""Search-quality eval harness: JSONL suites with P/R/MRR thresholds.

Reference: pkg/eval/harness.go:175-272 (Run/runTestCase), Thresholds
(harness.go:155), CLI cmd/eval. Suite format (one JSON object per
line):

    {"name": "case-1", "query": "tpu kernels",
     "expected": ["n1", "n7"], "limit": 10}

Metrics per case: precision@k, recall@k, reciprocal rank of the first
relevant hit; suite passes when the averages clear the thresholds.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence


@dataclass
class Thresholds:
    precision: float = 0.5
    recall: float = 0.5
    mrr: float = 0.5


@dataclass
class CaseResult:
    name: str
    precision: float
    recall: float
    reciprocal_rank: float
    returned: List[str] = field(default_factory=list)
    expected: List[str] = field(default_factory=list)


@dataclass
class SuiteResult:
    cases: List[CaseResult] = field(default_factory=list)
    thresholds: Thresholds = field(default_factory=Thresholds)

    @property
    def precision(self) -> float:
        return (sum(c.precision for c in self.cases) / len(self.cases)
                if self.cases else 0.0)

    @property
    def recall(self) -> float:
        return (sum(c.recall for c in self.cases) / len(self.cases)
                if self.cases else 0.0)

    @property
    def mrr(self) -> float:
        return (sum(c.reciprocal_rank for c in self.cases) / len(self.cases)
                if self.cases else 0.0)

    @property
    def passed(self) -> bool:
        t = self.thresholds
        return (bool(self.cases) and self.precision >= t.precision
                and self.recall >= t.recall and self.mrr >= t.mrr)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "cases": len(self.cases),
            "precision": round(self.precision, 4),
            "recall": round(self.recall, 4),
            "mrr": round(self.mrr, 4),
            "passed": self.passed,
            "failed_cases": [
                c.name for c in self.cases
                if c.reciprocal_rank == 0.0
            ],
        }


def score_case(
    name: str, returned: Sequence[str], expected: Sequence[str]
) -> CaseResult:
    rset = list(returned)
    eset = set(expected)
    hits = [r for r in rset if r in eset]
    precision = len(hits) / len(rset) if rset else 0.0
    recall = len(set(hits)) / len(eset) if eset else 1.0
    rr = 0.0
    for rank, r in enumerate(rset, start=1):
        if r in eset:
            rr = 1.0 / rank
            break
    return CaseResult(name=name, precision=precision, recall=recall,
                      reciprocal_rank=rr, returned=rset,
                      expected=list(expected))


class EvalHarness:
    """Runs a JSONL suite against a search callable
    (reference: Run/runTestCase harness.go:175-272)."""

    def __init__(self, search_fn, thresholds: Optional[Thresholds] = None):
        """search_fn(query: str, limit: int) -> List[str] of ids."""
        self.search_fn = search_fn
        self.thresholds = thresholds or Thresholds()

    def run_cases(self, cases: Sequence[Dict[str, Any]]) -> SuiteResult:
        suite = SuiteResult(thresholds=self.thresholds)
        for case in cases:
            limit = int(case.get("limit", 10))
            returned = self.search_fn(case.get("query", ""), limit)
            suite.cases.append(score_case(
                case.get("name", case.get("query", "?")),
                returned, case.get("expected", [])))
        return suite

    def run_file(self, path: str) -> SuiteResult:
        cases = []
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if line and not line.startswith("#"):
                    cases.append(json.loads(line))
        return self.run_cases(cases)


def harness_for_db(db, thresholds: Optional[Thresholds] = None,
                   mode: str = "hybrid") -> EvalHarness:
    def search_fn(query: str, limit: int) -> List[str]:
        return [str(h.get("id")) for h in
                db.search.search(query=query, limit=limit, mode=mode,
                                 enrich=False)]

    return EvalHarness(search_fn, thresholds)
