"""Layered configuration + runtime feature flags.

Re-expresses the reference's config stack (pkg/config/config.go:83-107:
defaults -> YAML -> ``NORNICDB_*`` env vars -> CLI flags; runtime-mutable
feature flags at pkg/config/feature_flags.go:118-501; per-database
overrides under pkg/config/dbconfig/) in one module. Precedence, lowest
to highest: built-in defaults, YAML file, environment, explicit
overrides (CLI flags pass through ``overrides``).
"""

from __future__ import annotations

import copy
import os
import threading
from dataclasses import dataclass, field, fields, is_dataclass
from typing import Any, Dict, List, Optional

ENV_PREFIX = "NORNICDB_"


def env_str(name: str, default: str = "") -> str:
    return os.environ.get(ENV_PREFIX + name, default)


def env_bool(name: str, default: bool = False) -> bool:
    v = os.environ.get(ENV_PREFIX + name)
    if v is None:
        return default
    return v.strip().lower() in ("1", "true", "yes", "on")


def env_int(name: str, default: int = 0) -> int:
    v = os.environ.get(ENV_PREFIX + name)
    if v is None:
        return default
    try:
        return int(v)
    except ValueError:
        return default


def env_float(name: str, default: float = 0.0) -> float:
    v = os.environ.get(ENV_PREFIX + name)
    if v is None:
        return default
    try:
        return float(v)
    except ValueError:
        return default


# ---------------------------------------------------------------------------
# Config sections (reference: pkg/config/config.go:83-107 — Auth/Database/
# Server/Memory/EmbeddingWorker/Compliance/Logging/Features)
# ---------------------------------------------------------------------------


@dataclass
class AuthConfig:
    enabled: bool = False
    jwt_secret: str = ""
    token_ttl_seconds: int = 3600
    allow_anonymous_reads: bool = False
    admin_user: str = "neo4j"
    admin_password: str = ""


@dataclass
class DatabaseConfig:
    data_dir: str = ""
    default_database: str = "neo4j"
    async_writes: bool = False
    sync_every_write: bool = False
    encryption_enabled: bool = False
    encryption_passphrase: str = ""
    wal_snapshot_interval_s: int = 300
    wal_max_segment_mb: int = 16
    max_databases: int = 64


@dataclass
class ServerConfig:
    http_host: str = "127.0.0.1"
    http_port: int = 7474
    bolt_port: int = 7687
    grpc_port: int = 6334
    cluster_port: int = 7688
    enable_bolt: bool = True
    enable_graphql: bool = True
    enable_mcp: bool = True
    enable_qdrant_grpc: bool = False
    rate_limit_per_minute: int = 0  # 0 = unlimited
    request_timeout_s: int = 30


@dataclass
class MemoryConfig:
    """AI-native memory behavior (decay tiers, auto-linking)."""

    decay_enabled: bool = True
    decay_interval_s: int = 3600
    episodic_half_life_days: float = 7.0
    semantic_half_life_days: float = 69.0
    procedural_half_life_days: float = 693.0
    archive_threshold: float = 0.05
    auto_link: bool = True
    auto_link_threshold: float = 0.82
    auto_link_max_per_node: int = 5


@dataclass
class EmbeddingConfig:
    provider: str = "local"  # local | http | none
    endpoint: str = ""
    model: str = "bge-m3"
    dims: int = 1024
    chunk_size: int = 512
    chunk_overlap: int = 50
    batch_size: int = 16
    workers: int = 2
    rescan_interval_s: int = 900
    cluster_debounce_s: int = 30


@dataclass
class SearchConfig:
    ann_quality: str = "balanced"  # fast | balanced | accurate | compressed
    gpu_enabled: bool = True  # device (TPU) acceleration
    gpu_threshold: int = 1024  # below this N, stay on host brute force
    hnsw_m: int = 16
    hnsw_ef_construction: int = 200
    hnsw_ef_search: int = 64
    rrf_k: int = 60
    rerank: str = "none"  # none | local | llm
    result_cache_size: int = 1024
    result_cache_ttl_s: int = 60


@dataclass
class ComplianceConfig:
    audit_enabled: bool = False
    audit_path: str = ""
    retention_days: int = 0  # 0 = keep forever
    gdpr_export_enabled: bool = True


@dataclass
class LoggingConfig:
    level: str = "info"
    json: bool = False


@dataclass
class ReplicationConfig:
    """Reference: pkg/replication/config.go:104-142."""

    mode: str = "standalone"  # standalone | ha_standby | raft | multi_region
    sync_mode: str = "async"  # async | quorum
    node_id: str = ""
    listen: str = ""
    peers: List[str] = field(default_factory=list)
    heartbeat_interval_s: float = 1.0
    election_timeout_s: float = 5.0


@dataclass
class Config:
    auth: AuthConfig = field(default_factory=AuthConfig)
    database: DatabaseConfig = field(default_factory=DatabaseConfig)
    server: ServerConfig = field(default_factory=ServerConfig)
    memory: MemoryConfig = field(default_factory=MemoryConfig)
    embedding: EmbeddingConfig = field(default_factory=EmbeddingConfig)
    search: SearchConfig = field(default_factory=SearchConfig)
    compliance: ComplianceConfig = field(default_factory=ComplianceConfig)
    logging: LoggingConfig = field(default_factory=LoggingConfig)
    replication: ReplicationConfig = field(default_factory=ReplicationConfig)

    def to_dict(self) -> Dict[str, Any]:
        return _dataclass_to_dict(self)

    def copy(self) -> "Config":
        return copy.deepcopy(self)


def _dataclass_to_dict(obj: Any) -> Any:
    if is_dataclass(obj):
        return {f.name: _dataclass_to_dict(getattr(obj, f.name)) for f in fields(obj)}
    if isinstance(obj, list):
        return [_dataclass_to_dict(x) for x in obj]
    return obj


def _apply_dict(obj: Any, data: Dict[str, Any]) -> None:
    """Merge a nested dict into a dataclass tree (unknown keys ignored)."""
    if not is_dataclass(obj) or not isinstance(data, dict):
        return
    by_name = {f.name: f for f in fields(obj)}
    for key, value in data.items():
        key = key.replace("-", "_")
        if key not in by_name:
            continue
        cur = getattr(obj, key)
        if is_dataclass(cur):
            _apply_dict(cur, value)
        elif value is not None:
            setattr(obj, key, _coerce_like(cur, value))


def _coerce_like(current: Any, value: Any) -> Any:
    """Coerce a YAML/override value to the field's existing type; a value
    that can't be coerced keeps the current setting (config must not plant
    type bombs for downstream consumers)."""
    try:
        if isinstance(current, bool):
            if isinstance(value, bool):
                return value
            return str(value).strip().lower() in ("1", "true", "yes", "on")
        if isinstance(current, int) and not isinstance(current, bool):
            return int(value)
        if isinstance(current, float):
            return float(value)
        if isinstance(current, str):
            return str(value)
        if isinstance(current, list):
            if isinstance(value, (list, tuple)):
                return list(value)
            if isinstance(value, str):
                # match NORNICDB_REPLICATION_PEERS-style comma lists
                return [p.strip() for p in value.split(",") if p.strip()]
            return current
    except (TypeError, ValueError):
        return current
    return value


# env var name -> (section attr, field attr, parser)
_ENV_MAP = {
    "AUTH_ENABLED": ("auth", "enabled", env_bool),
    "JWT_SECRET": ("auth", "jwt_secret", env_str),
    "ADMIN_PASSWORD": ("auth", "admin_password", env_str),
    "DATA_DIR": ("database", "data_dir", env_str),
    "DEFAULT_DATABASE": ("database", "default_database", env_str),
    "ASYNC_WRITES": ("database", "async_writes", env_bool),
    "SYNC_EVERY_WRITE": ("database", "sync_every_write", env_bool),
    "ENCRYPTION_ENABLED": ("database", "encryption_enabled", env_bool),
    "ENCRYPTION_PASSPHRASE": ("database", "encryption_passphrase", env_str),
    "HTTP_HOST": ("server", "http_host", env_str),
    "HTTP_PORT": ("server", "http_port", env_int),
    "BOLT_PORT": ("server", "bolt_port", env_int),
    "GRPC_PORT": ("server", "grpc_port", env_int),
    "CLUSTER_PORT": ("server", "cluster_port", env_int),
    "RATE_LIMIT_PER_MINUTE": ("server", "rate_limit_per_minute", env_int),
    "DECAY_ENABLED": ("memory", "decay_enabled", env_bool),
    "AUTO_LINK": ("memory", "auto_link", env_bool),
    "AUTO_LINK_THRESHOLD": ("memory", "auto_link_threshold", env_float),
    "EMBEDDING_PROVIDER": ("embedding", "provider", env_str),
    "EMBEDDING_ENDPOINT": ("embedding", "endpoint", env_str),
    "EMBEDDING_MODEL": ("embedding", "model", env_str),
    "EMBEDDING_DIMS": ("embedding", "dims", env_int),
    "EMBEDDING_CHUNK_SIZE": ("embedding", "chunk_size", env_int),
    "EMBEDDING_CHUNK_OVERLAP": ("embedding", "chunk_overlap", env_int),
    "EMBEDDING_WORKERS": ("embedding", "workers", env_int),
    "VECTOR_ANN_QUALITY": ("search", "ann_quality", env_str),
    "GPU_ENABLED": ("search", "gpu_enabled", env_bool),
    "GPU_THRESHOLD": ("search", "gpu_threshold", env_int),
    "RERANK": ("search", "rerank", env_str),
    "AUDIT_ENABLED": ("compliance", "audit_enabled", env_bool),
    "AUDIT_PATH": ("compliance", "audit_path", env_str),
    "RETENTION_DAYS": ("compliance", "retention_days", env_int),
    "LOG_LEVEL": ("logging", "level", env_str),
    "REPLICATION_MODE": ("replication", "mode", env_str),
    "REPLICATION_SYNC_MODE": ("replication", "sync_mode", env_str),
    "REPLICATION_NODE_ID": ("replication", "node_id", env_str),
    "REPLICATION_LISTEN": ("replication", "listen", env_str),
}


def load_config(
    yaml_path: Optional[str] = None,
    overrides: Optional[Dict[str, Any]] = None,
    env: bool = True,
) -> Config:
    """Build a Config with full precedence chain (reference:
    pkg/config/config.go:83-107)."""
    cfg = Config()
    if yaml_path and os.path.exists(yaml_path):
        import yaml  # baked-in

        with open(yaml_path, "r", encoding="utf-8") as f:
            data = yaml.safe_load(f) or {}
        _apply_dict(cfg, data)
    if env:
        for name, (section, attr, parser) in _ENV_MAP.items():
            if ENV_PREFIX + name in os.environ:
                section_obj = getattr(cfg, section)
                # malformed values keep the layered default, not the
                # parser's zero value
                setattr(section_obj, attr, parser(name, getattr(section_obj, attr)))
        peers = env_str("REPLICATION_PEERS")
        if peers:
            cfg.replication.peers = [p.strip() for p in peers.split(",") if p.strip()]
    if overrides:
        _apply_dict(cfg, overrides)
    return cfg


# ---------------------------------------------------------------------------
# Runtime feature flags (reference: pkg/config/feature_flags.go:118-501 —
# runtime-mutable, incl. parser mode, Kalman, AutoTLP, cooldown)
# ---------------------------------------------------------------------------

_FLAG_DEFAULTS: Dict[str, Any] = {
    "parser": "nornic",  # nornic | strict (reference: feature_flags.go:118,214)
    "kalman_decay": True,
    "auto_tlp": True,  # topology link prediction feeding inference
    "inference_cooldown": True,
    "query_cache": True,
    "fast_paths": True,
    "parallel_execution": True,
    "seed_hnsw_from_bm25": True,
    "search_diag_timings": False,
}


class FeatureFlags:
    """Thread-safe runtime-mutable flags. Env ``NORNICDB_FLAG_*`` (e.g.
    NORNICDB_FLAG_PARSER=strict) is consulted live on each read so import
    order doesn't freeze values; an explicit ``set()`` wins over env."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._explicit: Dict[str, Any] = {}

    def _from_env(self, name: str, default: Any) -> Any:
        raw = os.environ.get(ENV_PREFIX + "FLAG_" + name.upper())
        if raw is None:
            return default
        if isinstance(default, bool):
            return raw.strip().lower() in ("1", "true", "yes", "on")
        return raw

    def get(self, name: str, default: Any = None) -> Any:
        with self._lock:
            if name in self._explicit:
                return self._explicit[name]
        return self._from_env(name, _FLAG_DEFAULTS.get(name, default))

    def set(self, name: str, value: Any) -> None:
        with self._lock:
            self._explicit[name] = value

    def reset(self, name: Optional[str] = None) -> None:
        """Drop explicit overrides (all, or one flag) back to env/defaults."""
        with self._lock:
            if name is None:
                self._explicit.clear()
            else:
                self._explicit.pop(name, None)

    def all(self) -> Dict[str, Any]:
        return {k: self.get(k) for k in _FLAG_DEFAULTS}


flags = FeatureFlags()


# ---------------------------------------------------------------------------
# Per-database overrides (reference: pkg/config/dbconfig/ + admin API
# server_dbconfig.go) — a keyed bag of section overrides applied on top of
# the global config when a DB-scoped service asks for its view.
# ---------------------------------------------------------------------------


class DBConfigRegistry:
    def __init__(self, base: Config):
        self._base = base
        self._overrides: Dict[str, Dict[str, Any]] = {}
        self._lock = threading.Lock()

    def set_override(self, database: str, override: Dict[str, Any]) -> None:
        with self._lock:
            merged = self._overrides.setdefault(database, {})
            _deep_merge(merged, override)

    def clear_override(self, database: str) -> None:
        with self._lock:
            self._overrides.pop(database, None)

    def for_database(self, database: str) -> Config:
        with self._lock:
            override = copy.deepcopy(self._overrides.get(database, {}))
        cfg = self._base.copy()
        _apply_dict(cfg, override)
        return cfg

    def overrides(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return copy.deepcopy(self._overrides)


def decay_half_life_ms(mem: MemoryConfig) -> Dict[str, int]:
    """Translate MemoryConfig half-life days into the tier->ms map
    DecayManager consumes, so YAML/env half-life settings actually take
    effect (DecayManager(half_life_ms=decay_half_life_ms(cfg.memory)))."""
    from nornicdb_tpu.decay import DAY_MS, Tier

    return {
        Tier.EPISODIC: int(mem.episodic_half_life_days * DAY_MS),
        Tier.SEMANTIC: int(mem.semantic_half_life_days * DAY_MS),
        Tier.PROCEDURAL: int(mem.procedural_half_life_days * DAY_MS),
    }


def _deep_merge(dst: Dict[str, Any], src: Dict[str, Any]) -> None:
    for k, v in src.items():
        if isinstance(v, dict) and isinstance(dst.get(k), dict):
            _deep_merge(dst[k], v)
        else:
            dst[k] = v
