"""Plugin system: load Python plugins exposing functions + Heimdall hooks.

Reference: pkg/nornicdb/plugins.go — Go .so plugin loading with
reflection type-detection (LoadPluginsFromDir :59, detectPluginType
:207); function plugins become callable from Cypher
(PluginFunctionLookup db.go:992-999), Heimdall plugins hook generation.
The Python analog loads modules from a plugin directory and detects
their type by exported surface:

- **function plugin**: module defines ``FUNCTIONS = {"ns.name": fn}``
  (or ``register(db)``); functions become Cypher-callable.
- **heimdall plugin**: module defines a class/instance with an
  ``on_generate(prompt, text)`` hook.
"""

from __future__ import annotations

import importlib.util
import os
import sys
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional


@dataclass
class LoadedPlugin:
    name: str
    path: str
    kind: str  # function | heimdall | mixed | unknown
    functions: Dict[str, Callable] = field(default_factory=dict)
    heimdall_plugins: List[Any] = field(default_factory=list)
    error: Optional[str] = None


def detect_plugin_type(module) -> str:
    """Reference: detectPluginType (plugins.go:207) — inspect the
    exported surface instead of requiring a manifest."""
    has_fn = bool(getattr(module, "FUNCTIONS", None)) or callable(
        getattr(module, "register", None))
    has_heimdall = bool(getattr(module, "HEIMDALL_PLUGINS", None)) or (
        callable(getattr(module, "on_generate", None)))
    if has_fn and has_heimdall:
        return "mixed"
    if has_fn:
        return "function"
    if has_heimdall:
        return "heimdall"
    return "unknown"


def _load_module(path: str):
    name = "nornicdb_plugin_" + os.path.splitext(os.path.basename(path))[0]
    spec = importlib.util.spec_from_file_location(name, path)
    module = importlib.util.module_from_spec(spec)
    sys.modules[name] = module
    spec.loader.exec_module(module)
    return module


def load_plugins_from_dir(
    directory: str, db=None
) -> List[LoadedPlugin]:
    """Load every .py plugin in a directory (reference:
    LoadPluginsFromDir plugins.go:59). A broken plugin is reported, not
    fatal."""
    out: List[LoadedPlugin] = []
    if not os.path.isdir(directory):
        return out
    for fname in sorted(os.listdir(directory)):
        if not fname.endswith(".py") or fname.startswith("_"):
            continue
        path = os.path.join(directory, fname)
        name = os.path.splitext(fname)[0]
        try:
            module = _load_module(path)
        except Exception as e:
            out.append(LoadedPlugin(name=name, path=path, kind="unknown",
                                    error=f"{type(e).__name__}: {e}"))
            continue
        kind = detect_plugin_type(module)
        plugin = LoadedPlugin(name=name, path=path, kind=kind)
        fns = dict(getattr(module, "FUNCTIONS", {}) or {})
        register = getattr(module, "register", None)
        if callable(register):
            try:
                extra = register(db)
                if isinstance(extra, dict):
                    fns.update(extra)
            except Exception as e:
                plugin.error = f"register() failed: {e}"
        plugin.functions = fns
        hps = list(getattr(module, "HEIMDALL_PLUGINS", []) or [])
        if callable(getattr(module, "on_generate", None)):
            hps.append(module)
        plugin.heimdall_plugins = hps
        out.append(plugin)
    return out


def install_plugins(db, directory: str, heimdall_manager=None
                    ) -> List[LoadedPlugin]:
    """Load + wire: Cypher-callable functions onto the executor
    (reference: PluginFunctionLookup db.go:992-999), Heimdall hooks
    onto the manager."""
    plugins = load_plugins_from_dir(directory, db=db)
    for p in plugins:
        for name, fn in p.functions.items():
            db.executor.register_function(name, fn)
        if heimdall_manager is not None:
            for hp in p.heimdall_plugins:
                heimdall_manager.register_plugin(hp)
    return plugins
