"""Multi-database manager over one shared base engine.

Reference: pkg/multidb/manager.go:43 ``DatabaseManager`` with
CreateDatabase/DropDatabase/GetStorage (manager.go:300,339,388), per-DB
limits & enforcement (limits.go, enforcement.go), routing (routing.go).
Databases share one physical store via NamespacedEngine prefixes
(``dbname:``), so create/drop are metadata ops plus a prefix sweep.
"""

from __future__ import annotations

import re
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from nornicdb_tpu.errors import NotFoundError
from nornicdb_tpu.storage import Engine, ListenableEngine, NamespacedEngine

SYSTEM_DB = "system"
DEFAULT_DB = "neo4j"

_NAME_RE = re.compile(r"^[a-zA-Z][a-zA-Z0-9_.-]{0,62}$")


class DatabaseError(ValueError):
    pass


class DatabaseLimitExceeded(DatabaseError):
    """Reference: pkg/multidb/limits.go enforcement."""


@dataclass
class DatabaseLimits:
    """Per-database quotas (reference: limits.go StorageLimits +
    QueryLimits + ConnectionLimits + RateLimits). 0 = unlimited."""

    max_nodes: int = 0
    max_edges: int = 0
    max_bytes: int = 0              # exact serialized size (limits.go:59)
    max_results: int = 0            # rows returned per query
    max_queries_per_second: int = 0
    max_writes_per_second: int = 0
    max_concurrent_queries: int = 0  # QueryLimits.MaxConcurrentQueries
    max_connections: int = 0         # ConnectionLimits.MaxConnections

    def is_unlimited(self) -> bool:
        """Reference: limits.go:136 IsUnlimited."""
        return not any((
            self.max_nodes, self.max_edges, self.max_bytes,
            self.max_results, self.max_queries_per_second,
            self.max_writes_per_second, self.max_concurrent_queries,
            self.max_connections,
        ))


def entity_size(obj) -> int:
    """Exact serialized size of a node/edge for max_bytes accounting
    (reference: enforcement.go:344 calculateNodeSize — gob-serialized
    exact size, no estimation; here the canonical JSON encoding is the
    storage-format equivalent)."""
    import json

    if hasattr(obj, "labels"):
        payload = {"id": obj.id, "labels": obj.labels,
                   "properties": obj.properties}
    else:
        payload = {"id": obj.id, "type": obj.type,
                   "start": obj.start_node, "end": obj.end_node,
                   "properties": obj.properties}
    return len(json.dumps(payload, default=str,
                          separators=(",", ":")).encode("utf-8"))


@dataclass
class DatabaseInfo:
    name: str
    status: str = "online"  # online | offline
    default: bool = False
    system: bool = False
    limits: DatabaseLimits = field(default_factory=DatabaseLimits)


class LimitedEngine(NamespacedEngine):
    """NamespacedEngine that enforces per-DB node/edge/byte quotas on
    create (reference: pkg/multidb/enforcement.go). Byte accounting is
    exact and incremental — one initial scan, then O(1) per mutation
    (enforcement.go: 'Storage size is tracked incrementally for O(1)
    limit checks')."""

    def __init__(self, inner: Engine, database: str, limits: DatabaseLimits):
        super().__init__(inner, database)
        self._limits = limits
        self._bytes: Optional[int] = None  # lazy initial scan
        self._bytes_lock = threading.Lock()

    def _current_bytes_locked(self) -> int:
        if self._bytes is None:
            total = 0
            for n in self.all_nodes():
                total += entity_size(n)
            for e in self.all_edges():
                total += entity_size(e)
            self._bytes = total
        return self._bytes

    def _check_bytes(self, obj) -> int:
        size = entity_size(obj)
        with self._bytes_lock:
            current = self._current_bytes_locked()
            if current + size > self._limits.max_bytes:
                raise DatabaseLimitExceeded(
                    f"would exceed max_bytes limit (current: {current} "
                    f"bytes, limit: {self._limits.max_bytes} bytes, "
                    f"new entity: {size} bytes)")
        return size

    def _add_bytes(self, delta: int) -> None:
        with self._bytes_lock:
            if self._bytes is not None:
                self._bytes = max(0, self._bytes + delta)

    def create_node(self, node):
        lim = self._limits
        if lim.max_nodes and self.count_nodes() >= lim.max_nodes:
            raise DatabaseLimitExceeded(
                f"database has reached max_nodes limit "
                f"({self.count_nodes()}/{lim.max_nodes})")
        size = self._check_bytes(node) if lim.max_bytes else 0
        super().create_node(node)
        if lim.max_bytes:
            self._add_bytes(size)

    def create_edge(self, edge):
        lim = self._limits
        if lim.max_edges and self.count_edges() >= lim.max_edges:
            raise DatabaseLimitExceeded(
                f"database has reached max_edges limit "
                f"({self.count_edges()}/{lim.max_edges})")
        size = self._check_bytes(edge) if lim.max_bytes else 0
        super().create_edge(edge)
        if lim.max_bytes:
            self._add_bytes(size)

    def update_node(self, node):
        if self._limits.max_bytes:
            try:
                old = entity_size(self.get_node(node.id))
            except Exception:
                old = 0
            self._add_bytes(entity_size(node) - old)
        super().update_node(node)

    def delete_node(self, node_id):
        if self._limits.max_bytes:
            try:
                self._add_bytes(-entity_size(self.get_node(node_id)))
            except Exception:
                pass
        super().delete_node(node_id)

    def delete_edge(self, edge_id):
        if self._limits.max_bytes:
            try:
                self._add_bytes(-entity_size(self.get_edge(edge_id)))
            except Exception:
                pass
        super().delete_edge(edge_id)

    def current_bytes(self) -> int:
        """Exact tracked storage size (enforcement.go:244)."""
        with self._bytes_lock:
            return self._current_bytes_locked()


class ConnectionTracker:
    """Per-database connection counting against MaxConnections
    (reference: enforcement.go:513 ConnectionTracker)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {}

    def try_increment(self, manager: "DatabaseManager", name: str) -> None:
        lim = manager.get_info(name).limits
        with self._lock:
            cur = self._counts.get(name, 0)
            if lim.max_connections and cur >= lim.max_connections:
                raise DatabaseLimitExceeded(
                    f"database {name!r} has reached max_connections "
                    f"limit ({cur}/{lim.max_connections})")
            self._counts[name] = cur + 1

    def decrement(self, name: str) -> None:
        with self._lock:
            cur = self._counts.get(name, 0)
            if cur <= 1:
                self._counts.pop(name, None)
            else:
                self._counts[name] = cur - 1

    def count(self, name: str) -> int:
        with self._lock:
            return self._counts.get(name, 0)


class DatabaseManager:
    """Create/drop/list logical databases over one base engine."""

    def __init__(self, base: Engine, default_database: str = DEFAULT_DB,
                 max_databases: int = 64):
        self._base = base
        self._max = max_databases
        self._lock = threading.Lock()
        self._dbs: Dict[str, DatabaseInfo] = {}
        self._engines: Dict[str, ListenableEngine] = {}
        # per-db (window_second, queries, writes) for rate enforcement
        self._rate_windows: Dict[str, tuple] = {}
        # per-db in-flight query counts (MaxConcurrentQueries)
        self._active_queries: Dict[str, int] = {}
        self._dbs[SYSTEM_DB] = DatabaseInfo(name=SYSTEM_DB, system=True)
        self._dbs[default_database] = DatabaseInfo(name=default_database, default=True)
        # adopt pre-existing namespaces found in the store (restart path)
        for ns in base.list_namespaces():
            if ns not in self._dbs and _NAME_RE.match(ns):
                self._dbs[ns] = DatabaseInfo(name=ns)

    # -- lifecycle -------------------------------------------------------

    def create_database(self, name: str, limits: Optional[DatabaseLimits] = None,
                        if_not_exists: bool = False) -> DatabaseInfo:
        with self._lock:
            if not _NAME_RE.match(name):
                raise DatabaseError(f"invalid database name: {name!r}")
            if name in self._dbs:
                if self._dbs[name].status == "dropping":
                    raise DatabaseError(f"database being dropped: {name}")
                if if_not_exists:
                    return self._dbs[name]
                raise DatabaseError(f"database exists: {name}")
            user_dbs = sum(1 for d in self._dbs.values() if not d.system)
            if self._max and user_dbs >= self._max:
                raise DatabaseLimitExceeded(f"max databases ({self._max}) reached")
            info = DatabaseInfo(name=name, limits=limits or DatabaseLimits())
            self._dbs[name] = info
            return info

    def drop_database(self, name: str, if_exists: bool = False) -> bool:
        with self._lock:
            info = self._dbs.get(name)
            if info is None:
                if if_exists:
                    return False
                raise NotFoundError(f"database not found: {name}")
            if info.system:
                raise DatabaseError("cannot drop system database")
            if info.default:
                raise DatabaseError("cannot drop default database")
            if info.status == "dropping":
                raise DatabaseError(f"database already being dropped: {name}")
            # keep the entry as a tombstone until the sweep finishes so a
            # concurrent create_database(name) can't race the deletion
            info.status = "dropping"
            self._engines.pop(name, None)
            self._rate_windows.pop(name, None)
        try:
            # prefix sweep outside the lock — can be large
            self._base.delete_by_prefix(name + ":")
        except BaseException:
            # failed sweep: keep the tombstone so the undeleted rows can't
            # reappear inside a freshly recreated database; a retry of
            # drop_database is blocked with "already being dropped" until
            # an operator resolves it, which is the safe failure mode
            raise
        with self._lock:
            self._dbs.pop(name, None)
        return True

    def list_databases(self) -> List[DatabaseInfo]:
        with self._lock:
            return sorted(self._dbs.values(), key=lambda d: d.name)

    def exists(self, name: str) -> bool:
        with self._lock:
            return name in self._dbs

    def get_info(self, name: str) -> DatabaseInfo:
        with self._lock:
            info = self._dbs.get(name)
        if info is None:
            raise NotFoundError(f"database not found: {name}")
        return info

    def set_status(self, name: str, status: str) -> None:
        if status not in ("online", "offline"):
            raise DatabaseError(f"invalid status: {status}")
        info = self.get_info(name)
        info.status = status

    def set_limits(self, name: str, limits: DatabaseLimits) -> None:
        info = self.get_info(name)
        with self._lock:
            info.limits = limits
            self._engines.pop(name, None)  # rebuild with new limits

    # -- routing (reference: routing.go) ---------------------------------

    def get_storage(self, name: str) -> ListenableEngine:
        """Namespaced, limit-enforcing, listenable view of one database
        (reference: manager.go:388 GetStorage)."""
        with self._lock:
            info = self._dbs.get(name)
            if info is None:
                raise NotFoundError(f"database not found: {name}")
            if info.status != "online":
                raise DatabaseError(f"database offline: {name}")
            eng = self._engines.get(name)
            if eng is None:
                eng = ListenableEngine(LimitedEngine(self._base, name, info.limits))
                self._engines[name] = eng
            return eng

    def enforce_query(self, name: str, is_write: bool = False) -> None:
        """Per-database rate limiting (reference: enforcement.go; fixed
        one-second windows). Raises DatabaseLimitExceeded when the
        database's query or write rate is exhausted."""
        info = self.get_info(name)
        lim = info.limits
        if not (lim.max_queries_per_second or lim.max_writes_per_second):
            return
        now = int(time.time())
        with self._lock:
            win, q, w = self._rate_windows.get(name, (now, 0, 0))
            if win != now:
                win, q, w = now, 0, 0
            q += 1
            if is_write:
                w += 1
            self._rate_windows[name] = (win, q, w)
        if lim.max_queries_per_second and q > lim.max_queries_per_second:
            raise DatabaseLimitExceeded(
                f"database {name!r} query rate limit "
                f"{lim.max_queries_per_second}/s exceeded")
        if is_write and lim.max_writes_per_second and (
            w > lim.max_writes_per_second
        ):
            raise DatabaseLimitExceeded(
                f"database {name!r} write rate limit "
                f"{lim.max_writes_per_second}/s exceeded")

    def query_slot(self, name: str):
        """Context manager enforcing MaxConcurrentQueries (reference:
        enforcement.go:382 CheckQueryLimits): entering counts the query
        against the database's concurrency cap, exiting releases it."""
        manager = self

        class _Slot:
            def __enter__(self):
                lim = manager.get_info(name).limits
                with manager._lock:
                    cur = manager._active_queries.get(name, 0)
                    if (lim.max_concurrent_queries
                            and cur >= lim.max_concurrent_queries):
                        raise DatabaseLimitExceeded(
                            f"database {name!r} has reached "
                            f"max_concurrent_queries limit "
                            f"({cur}/{lim.max_concurrent_queries})")
                    manager._active_queries[name] = cur + 1
                return self

            def __exit__(self, *exc):
                with manager._lock:
                    cur = manager._active_queries.get(name, 1)
                    if cur <= 1:
                        manager._active_queries.pop(name, None)
                    else:
                        manager._active_queries[name] = cur - 1
                return False

        return _Slot()

    # -- legacy migration (reference: migration.go:53) --------------------

    MIGRATION_MARKER = "system:__migration_complete__"

    def is_migration_complete(self) -> bool:
        """Reference: migration.go:98."""
        try:
            return self._base.get_node(self.MIGRATION_MARKER) is not None
        except Exception:
            return False

    def migrate_legacy_data(self, target: Optional[str] = None) -> Dict[str, int]:
        """Move unprefixed (pre-multidb) nodes/edges under the default
        database's namespace (reference: migration.go:53
        migrateLegacyData + detectUnprefixedData + performMigration).
        Idempotent: a completion marker in the system namespace skips
        re-scans on every boot."""
        from nornicdb_tpu.storage.types import Node

        if self.is_migration_complete():
            return {"nodes": 0, "edges": 0, "skipped": 1}
        target = target or next(
            d.name for d in self._dbs.values() if d.default)
        prefix = target + ":"
        known = {d + ":" for d in self._dbs}
        moved_nodes = moved_edges = 0
        legacy_nodes = [
            n for n in self._base.all_nodes()
            if not any(n.id.startswith(p) for p in known)
            and n.id != self.MIGRATION_MARKER
        ]
        legacy_edges = [
            e for e in self._base.all_edges()
            if not any(e.id.startswith(p) for p in known)
        ]
        # create prefixed copies first, then re-point edges, then drop
        # the originals — an interrupted migration leaves duplicates (a
        # re-run converges) rather than data loss
        for n in legacy_nodes:
            c = n.copy()
            c.id = prefix + c.id
            self._base.create_node(c)
            moved_nodes += 1
        for e in legacy_edges:
            c = e.copy()
            c.id = prefix + c.id
            if not any(c.start_node.startswith(p) for p in known):
                c.start_node = prefix + c.start_node
            if not any(c.end_node.startswith(p) for p in known):
                c.end_node = prefix + c.end_node
            self._base.create_edge(c)
            moved_edges += 1
        for e in legacy_edges:
            self._base.delete_edge(e.id)
        for n in legacy_nodes:
            self._base.delete_node(n.id)
        self._base.create_node(Node(
            id=self.MIGRATION_MARKER, labels=["_Migration"],
            properties={"completed": True},
        ))
        return {"nodes": moved_nodes, "edges": moved_edges, "skipped": 0}

    def truncate_result(self, name: str, result) -> None:
        """Cap result rows at the database's max_results (reference:
        QueryLimits.MaxResults)."""
        lim = self.get_info(name).limits
        if lim.max_results and len(result.rows) > lim.max_results:
            del result.rows[lim.max_results:]

    def counts(self, name: str) -> Dict[str, int]:
        eng = self.get_storage(name)
        return {"nodes": eng.count_nodes(), "edges": eng.count_edges()}
