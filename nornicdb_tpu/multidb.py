"""Multi-database manager over one shared base engine.

Reference: pkg/multidb/manager.go:43 ``DatabaseManager`` with
CreateDatabase/DropDatabase/GetStorage (manager.go:300,339,388), per-DB
limits & enforcement (limits.go, enforcement.go), routing (routing.go).
Databases share one physical store via NamespacedEngine prefixes
(``dbname:``), so create/drop are metadata ops plus a prefix sweep.
"""

from __future__ import annotations

import re
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from nornicdb_tpu.errors import NotFoundError
from nornicdb_tpu.storage import Engine, ListenableEngine, NamespacedEngine

SYSTEM_DB = "system"
DEFAULT_DB = "neo4j"

_NAME_RE = re.compile(r"^[a-zA-Z][a-zA-Z0-9_.-]{0,62}$")


class DatabaseError(ValueError):
    pass


class DatabaseLimitExceeded(DatabaseError):
    """Reference: pkg/multidb/limits.go enforcement."""


@dataclass
class DatabaseLimits:
    """Per-database quotas (reference: limits.go StorageLimits +
    QueryLimits + RateLimits). 0 = unlimited."""

    max_nodes: int = 0
    max_edges: int = 0
    max_results: int = 0            # rows returned per query
    max_queries_per_second: int = 0
    max_writes_per_second: int = 0


@dataclass
class DatabaseInfo:
    name: str
    status: str = "online"  # online | offline
    default: bool = False
    system: bool = False
    limits: DatabaseLimits = field(default_factory=DatabaseLimits)


class LimitedEngine(NamespacedEngine):
    """NamespacedEngine that enforces per-DB node/edge quotas on create
    (reference: pkg/multidb/enforcement.go)."""

    def __init__(self, inner: Engine, database: str, limits: DatabaseLimits):
        super().__init__(inner, database)
        self._limits = limits

    def create_node(self, node):
        if self._limits.max_nodes and self.count_nodes() >= self._limits.max_nodes:
            raise DatabaseLimitExceeded(
                f"database node limit {self._limits.max_nodes} reached")
        super().create_node(node)

    def create_edge(self, edge):
        if self._limits.max_edges and self.count_edges() >= self._limits.max_edges:
            raise DatabaseLimitExceeded(
                f"database edge limit {self._limits.max_edges} reached")
        super().create_edge(edge)


class DatabaseManager:
    """Create/drop/list logical databases over one base engine."""

    def __init__(self, base: Engine, default_database: str = DEFAULT_DB,
                 max_databases: int = 64):
        self._base = base
        self._max = max_databases
        self._lock = threading.Lock()
        self._dbs: Dict[str, DatabaseInfo] = {}
        self._engines: Dict[str, ListenableEngine] = {}
        # per-db (window_second, queries, writes) for rate enforcement
        self._rate_windows: Dict[str, tuple] = {}
        self._dbs[SYSTEM_DB] = DatabaseInfo(name=SYSTEM_DB, system=True)
        self._dbs[default_database] = DatabaseInfo(name=default_database, default=True)
        # adopt pre-existing namespaces found in the store (restart path)
        for ns in base.list_namespaces():
            if ns not in self._dbs and _NAME_RE.match(ns):
                self._dbs[ns] = DatabaseInfo(name=ns)

    # -- lifecycle -------------------------------------------------------

    def create_database(self, name: str, limits: Optional[DatabaseLimits] = None,
                        if_not_exists: bool = False) -> DatabaseInfo:
        with self._lock:
            if not _NAME_RE.match(name):
                raise DatabaseError(f"invalid database name: {name!r}")
            if name in self._dbs:
                if self._dbs[name].status == "dropping":
                    raise DatabaseError(f"database being dropped: {name}")
                if if_not_exists:
                    return self._dbs[name]
                raise DatabaseError(f"database exists: {name}")
            user_dbs = sum(1 for d in self._dbs.values() if not d.system)
            if self._max and user_dbs >= self._max:
                raise DatabaseLimitExceeded(f"max databases ({self._max}) reached")
            info = DatabaseInfo(name=name, limits=limits or DatabaseLimits())
            self._dbs[name] = info
            return info

    def drop_database(self, name: str, if_exists: bool = False) -> bool:
        with self._lock:
            info = self._dbs.get(name)
            if info is None:
                if if_exists:
                    return False
                raise NotFoundError(f"database not found: {name}")
            if info.system:
                raise DatabaseError("cannot drop system database")
            if info.default:
                raise DatabaseError("cannot drop default database")
            if info.status == "dropping":
                raise DatabaseError(f"database already being dropped: {name}")
            # keep the entry as a tombstone until the sweep finishes so a
            # concurrent create_database(name) can't race the deletion
            info.status = "dropping"
            self._engines.pop(name, None)
            self._rate_windows.pop(name, None)
        try:
            # prefix sweep outside the lock — can be large
            self._base.delete_by_prefix(name + ":")
        except BaseException:
            # failed sweep: keep the tombstone so the undeleted rows can't
            # reappear inside a freshly recreated database; a retry of
            # drop_database is blocked with "already being dropped" until
            # an operator resolves it, which is the safe failure mode
            raise
        with self._lock:
            self._dbs.pop(name, None)
        return True

    def list_databases(self) -> List[DatabaseInfo]:
        with self._lock:
            return sorted(self._dbs.values(), key=lambda d: d.name)

    def exists(self, name: str) -> bool:
        with self._lock:
            return name in self._dbs

    def get_info(self, name: str) -> DatabaseInfo:
        with self._lock:
            info = self._dbs.get(name)
        if info is None:
            raise NotFoundError(f"database not found: {name}")
        return info

    def set_status(self, name: str, status: str) -> None:
        if status not in ("online", "offline"):
            raise DatabaseError(f"invalid status: {status}")
        info = self.get_info(name)
        info.status = status

    def set_limits(self, name: str, limits: DatabaseLimits) -> None:
        info = self.get_info(name)
        with self._lock:
            info.limits = limits
            self._engines.pop(name, None)  # rebuild with new limits

    # -- routing (reference: routing.go) ---------------------------------

    def get_storage(self, name: str) -> ListenableEngine:
        """Namespaced, limit-enforcing, listenable view of one database
        (reference: manager.go:388 GetStorage)."""
        with self._lock:
            info = self._dbs.get(name)
            if info is None:
                raise NotFoundError(f"database not found: {name}")
            if info.status != "online":
                raise DatabaseError(f"database offline: {name}")
            eng = self._engines.get(name)
            if eng is None:
                eng = ListenableEngine(LimitedEngine(self._base, name, info.limits))
                self._engines[name] = eng
            return eng

    def enforce_query(self, name: str, is_write: bool = False) -> None:
        """Per-database rate limiting (reference: enforcement.go; fixed
        one-second windows). Raises DatabaseLimitExceeded when the
        database's query or write rate is exhausted."""
        info = self.get_info(name)
        lim = info.limits
        if not (lim.max_queries_per_second or lim.max_writes_per_second):
            return
        now = int(time.time())
        with self._lock:
            win, q, w = self._rate_windows.get(name, (now, 0, 0))
            if win != now:
                win, q, w = now, 0, 0
            q += 1
            if is_write:
                w += 1
            self._rate_windows[name] = (win, q, w)
        if lim.max_queries_per_second and q > lim.max_queries_per_second:
            raise DatabaseLimitExceeded(
                f"database {name!r} query rate limit "
                f"{lim.max_queries_per_second}/s exceeded")
        if is_write and lim.max_writes_per_second and (
            w > lim.max_writes_per_second
        ):
            raise DatabaseLimitExceeded(
                f"database {name!r} write rate limit "
                f"{lim.max_writes_per_second}/s exceeded")

    def truncate_result(self, name: str, result) -> None:
        """Cap result rows at the database's max_results (reference:
        QueryLimits.MaxResults)."""
        lim = self.get_info(name).limits
        if lim.max_results and len(result.rows) > lim.max_results:
            del result.rows[lim.max_results:]

    def counts(self, name: str) -> Dict[str, int]:
        eng = self.get_storage(name)
        return {"nodes": eng.count_nodes(), "edges": eng.count_edges()}
