"""Write-behind (async) engine decorator.

Buffers mutations in an in-RAM overlay and flushes them to the inner engine
on a background interval, giving fast ack-on-write with eventual
consistency — reads merge the overlay so the writer always sees its own
writes. Reference: pkg/storage/async_engine.go:28 ``AsyncEngine``,
``NewAsyncEngine`` :207, ``FlushResult`` :294.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from nornicdb_tpu.errors import AlreadyExistsError, NotFoundError

logger = logging.getLogger(__name__)
from nornicdb_tpu.storage.types import (
    Direction,
    Edge,
    EdgeID,
    Engine,
    EngineDecorator,
    Node,
    NodeID,
    now_ms,
)

_TOMBSTONE = object()


@dataclass
class FlushResult:
    ops_flushed: int = 0
    errors: List[str] = field(default_factory=list)


class AsyncEngine(EngineDecorator):
    def __init__(self, inner: Engine, flush_interval_s: float = 0.1, max_pending: int = 10_000):
        super().__init__(inner)
        self.flush_interval_s = flush_interval_s
        self.max_pending = max_pending
        self._lock = threading.RLock()
        self._ops: List[Tuple[str, object]] = []
        self._nodes: Dict[NodeID, object] = {}  # Node or _TOMBSTONE
        self._edges: Dict[EdgeID, object] = {}  # Edge or _TOMBSTONE
        self.last_flush_errors: List[str] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if flush_interval_s > 0:
            self._thread = threading.Thread(
                target=self._flush_loop, name="async-engine-flush", daemon=True
            )
            self._thread.start()

    # -- background flush ------------------------------------------------

    def _flush_loop(self) -> None:
        while not self._stop.wait(self.flush_interval_s):
            try:
                res = self.flush_pending()
                for err in res.errors:
                    logger.error("async flush error (write lost): %s", err)
                    self.last_flush_errors.append(err)
            except Exception:
                logger.exception("async flush loop failure")

    def flush_pending(self) -> FlushResult:
        """Drain buffered ops into the inner engine, preserving order.

        IMPORTANT: ops are applied OUTSIDE the overlay lock, and the overlay
        is only cleared for entries not re-dirtied during the flush — this
        avoids the callback/flush deadlocks and lost-count races the
        reference's regression suite memorializes
        (async_engine_count_flush_race_test.go, async_engine_callback_deadlock_test.go).
        """
        with self._lock:
            ops = self._ops
            self._ops = []
        res = FlushResult()
        for kind, payload in ops:
            try:
                if kind == "upsert_node":
                    node = payload  # type: ignore[assignment]
                    try:
                        self.inner.update_node(node)
                    except KeyError:
                        self.inner.create_node(node)
                elif kind == "delete_node":
                    try:
                        self.inner.delete_node(payload)  # type: ignore[arg-type]
                    except KeyError:
                        pass
                elif kind == "upsert_edge":
                    edge = payload  # type: ignore[assignment]
                    try:
                        self.inner.update_edge(edge)
                    except KeyError:
                        self.inner.create_edge(edge)
                elif kind == "delete_edge":
                    try:
                        self.inner.delete_edge(payload)  # type: ignore[arg-type]
                    except KeyError:
                        pass
                res.ops_flushed += 1
            except Exception as exc:  # keep flushing; record error
                res.errors.append(f"{kind}: {exc}")
        with self._lock:
            # clear overlay entries that were not re-dirtied meanwhile
            dirty_nodes = {
                op[1].id if isinstance(op[1], Node) else op[1]
                for op in self._ops
                if op[0] in ("upsert_node", "delete_node")
            }
            dirty_edges = {
                op[1].id if isinstance(op[1], Edge) else op[1]
                for op in self._ops
                if op[0] in ("upsert_edge", "delete_edge")
            }
            for nid in list(self._nodes):
                if nid not in dirty_nodes:
                    del self._nodes[nid]
            for eid in list(self._edges):
                if eid not in dirty_edges:
                    del self._edges[eid]
        return res

    def flush(self) -> None:
        self.flush_pending()
        self.inner.flush()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self.flush_pending()
        self.inner.close()

    def _over_pending(self) -> bool:
        """Whether the op buffer is over the backpressure threshold.

        Checked under the lock; the flush itself runs OUTSIDE the lock so a
        writer hitting backpressure doesn't stall readers for the whole
        flush (the invariant flush_pending documents)."""
        return len(self._ops) >= self.max_pending

    # -- nodes -----------------------------------------------------------

    def create_node(self, node: Node) -> None:
        n = node.copy()
        if not n.created_at:
            n.created_at = now_ms()
        if not n.updated_at:
            n.updated_at = n.created_at
        with self._lock:
            ov = self._nodes.get(n.id)
            exists = isinstance(ov, Node) or (
                ov is not _TOMBSTONE and self.inner.has_node(n.id)
            )
            if exists:
                raise AlreadyExistsError(f"node {n.id} already exists")
            self._nodes[n.id] = n
            self._ops.append(("upsert_node", n))
            bp = self._over_pending()
        if bp:
            self.flush_pending()

    def update_node(self, node: Node) -> None:
        n = node.copy()
        n.updated_at = now_ms()
        with self._lock:
            self._nodes[n.id] = n
            self._ops.append(("upsert_node", n))
            bp = self._over_pending()
        if bp:
            self.flush_pending()

    def delete_node(self, node_id: NodeID) -> None:
        with self._lock:
            self._nodes[node_id] = _TOMBSTONE
            # tombstone attached edges in the overlay as well
            for eid, ov in list(self._edges.items()):
                if isinstance(ov, Edge) and node_id in (ov.start_node, ov.end_node):
                    self._edges[eid] = _TOMBSTONE
            self._ops.append(("delete_node", node_id))
            bp = self._over_pending()
        if bp:
            self.flush_pending()

    def get_node(self, node_id: NodeID) -> Node:
        with self._lock:
            ov = self._nodes.get(node_id)
        if ov is _TOMBSTONE:
            raise NotFoundError(f"node {node_id} not found")
        if isinstance(ov, Node):
            return ov.copy()
        return self.inner.get_node(node_id)

    def has_node(self, node_id: NodeID) -> bool:
        with self._lock:
            ov = self._nodes.get(node_id)
        if ov is _TOMBSTONE:
            return False
        if isinstance(ov, Node):
            return True
        return self.inner.has_node(node_id)

    def has_edge(self, edge_id: EdgeID) -> bool:
        with self._lock:
            ov = self._edges.get(edge_id)
        if ov is _TOMBSTONE:
            return False
        if isinstance(ov, Edge):
            return True
        return self.inner.has_edge(edge_id)

    def get_nodes_by_label(self, label: str) -> List[Node]:
        base = {n.id: n for n in self.inner.get_nodes_by_label(label)}
        with self._lock:
            overlay = dict(self._nodes)
        for nid, ov in overlay.items():
            if ov is _TOMBSTONE:
                base.pop(nid, None)
            elif isinstance(ov, Node):
                if label in ov.labels:
                    base[nid] = ov
                else:
                    base.pop(nid, None)
        return [n.copy() for n in base.values()]

    def node_ids_by_label(self, label: str) -> List[NodeID]:
        ids = set(self.inner.node_ids_by_label(label))
        with self._lock:
            overlay = dict(self._nodes)
        for nid, ov in overlay.items():
            if ov is _TOMBSTONE:
                ids.discard(nid)
            elif isinstance(ov, Node):
                if label in ov.labels:
                    ids.add(nid)
                else:
                    ids.discard(nid)
        return list(ids)

    def all_nodes(self) -> Iterable[Node]:
        base = {n.id: n for n in self.inner.all_nodes()}
        with self._lock:
            overlay = dict(self._nodes)
        for nid, ov in overlay.items():
            if ov is _TOMBSTONE:
                base.pop(nid, None)
            elif isinstance(ov, Node):
                base[nid] = ov
        return [n.copy() for n in base.values()]

    def batch_get_nodes(self, node_ids: Sequence[NodeID]) -> List[Optional[Node]]:
        with self._lock:
            overlay = dict(self._nodes)
        missing = [i for i in node_ids if i not in overlay]
        fetched = dict(zip(missing, self.inner.batch_get_nodes(missing)))
        out: List[Optional[Node]] = []
        for nid in node_ids:
            ov = overlay.get(nid)
            if ov is _TOMBSTONE:
                out.append(None)
            elif isinstance(ov, Node):
                out.append(ov.copy())
            else:
                out.append(fetched.get(nid))
        return out

    # -- edges -----------------------------------------------------------

    def create_edge(self, edge: Edge) -> None:
        e = edge.copy()
        if not e.created_at:
            e.created_at = now_ms()
        if not e.updated_at:
            e.updated_at = e.created_at
        with self._lock:
            ov = self._edges.get(e.id)
            exists = isinstance(ov, Edge) or (
                ov is not _TOMBSTONE and self.inner.has_edge(e.id)
            )
            if exists:
                raise AlreadyExistsError(f"edge {e.id} already exists")
            dead = self._dead_node_ids()
            for endpoint in (e.start_node, e.end_node):
                present = (
                    isinstance(self._nodes.get(endpoint), Node)
                    or (endpoint not in dead and self.inner.has_node(endpoint))
                )
                if not present:
                    raise NotFoundError(f"node {endpoint} not found")
            self._edges[e.id] = e
            self._ops.append(("upsert_edge", e))
            bp = self._over_pending()
        if bp:
            self.flush_pending()

    def update_edge(self, edge: Edge) -> None:
        e = edge.copy()
        e.updated_at = now_ms()
        with self._lock:
            self._edges[e.id] = e
            self._ops.append(("upsert_edge", e))
            bp = self._over_pending()
        if bp:
            self.flush_pending()

    def delete_edge(self, edge_id: EdgeID) -> None:
        with self._lock:
            self._edges[edge_id] = _TOMBSTONE
            self._ops.append(("delete_edge", edge_id))
            bp = self._over_pending()
        if bp:
            self.flush_pending()

    def get_edge(self, edge_id: EdgeID) -> Edge:
        with self._lock:
            ov = self._edges.get(edge_id)
        if ov is _TOMBSTONE:
            raise NotFoundError(f"edge {edge_id} not found")
        if isinstance(ov, Edge):
            return ov.copy()
        return self.inner.get_edge(edge_id)

    def _dead_node_ids(self) -> Set[NodeID]:
        """Node IDs tombstoned in the overlay (their inner edges must be
        masked from reads until the delete flushes)."""
        return {nid for nid, ov in self._nodes.items() if ov is _TOMBSTONE}

    def _drop_edges_of_dead_nodes(self, base: Dict[EdgeID, Edge]) -> None:
        with self._lock:
            dead = self._dead_node_ids()
        if not dead:
            return
        for eid in list(base):
            e = base[eid]
            if e.start_node in dead or e.end_node in dead:
                del base[eid]

    def get_edges_by_type(self, edge_type: str) -> List[Edge]:
        base = {e.id: e for e in self.inner.get_edges_by_type(edge_type)}
        self._drop_edges_of_dead_nodes(base)
        with self._lock:
            overlay = dict(self._edges)
        for eid, ov in overlay.items():
            if ov is _TOMBSTONE:
                base.pop(eid, None)
            elif isinstance(ov, Edge):
                if ov.type == edge_type:
                    base[eid] = ov
                else:
                    base.pop(eid, None)
        return [e.copy() for e in base.values()]

    def all_edges(self) -> Iterable[Edge]:
        base = {e.id: e for e in self.inner.all_edges()}
        self._drop_edges_of_dead_nodes(base)
        with self._lock:
            overlay = dict(self._edges)
        for eid, ov in overlay.items():
            if ov is _TOMBSTONE:
                base.pop(eid, None)
            elif isinstance(ov, Edge):
                base[eid] = ov
        return [e.copy() for e in base.values()]

    def get_node_edges(
        self, node_id: NodeID, direction: str = Direction.BOTH
    ) -> List[Edge]:
        base = {e.id: e for e in self.inner.get_node_edges(node_id, direction)}
        self._drop_edges_of_dead_nodes(base)
        with self._lock:
            overlay = dict(self._edges)
        for eid, ov in overlay.items():
            if ov is _TOMBSTONE:
                base.pop(eid, None)
            elif isinstance(ov, Edge):
                touches = (
                    direction in (Direction.OUTGOING, Direction.BOTH)
                    and ov.start_node == node_id
                ) or (
                    direction in (Direction.INCOMING, Direction.BOTH)
                    and ov.end_node == node_id
                )
                if touches:
                    base[eid] = ov
                else:
                    base.pop(eid, None)
        return [e.copy() for e in base.values()]

    def degree(self, node_id: NodeID, direction: str = Direction.BOTH) -> int:
        return len(self.get_node_edges(node_id, direction))

    # -- counts (overlay-aware: the count-flush race fix) -----------------

    def count_nodes(self) -> int:
        with self._lock:
            overlay = dict(self._nodes)
        inner_count = self.inner.count_nodes()
        delta = 0
        for nid, ov in overlay.items():
            exists_inner = self._inner_has_node(nid)
            if ov is _TOMBSTONE and exists_inner:
                delta -= 1
            elif isinstance(ov, Node) and not exists_inner:
                delta += 1
        return inner_count + delta

    def count_edges(self) -> int:
        with self._lock:
            overlay = dict(self._edges)
            dead = self._dead_node_ids()
        if dead:
            # unflushed node deletes mask inner edges; count via merge
            return len(list(self.all_edges()))
        inner_count = self.inner.count_edges()
        delta = 0
        for eid, ov in overlay.items():
            exists_inner = self._inner_has_edge(eid)
            if ov is _TOMBSTONE and exists_inner:
                delta -= 1
            elif isinstance(ov, Edge) and not exists_inner:
                delta += 1
        return inner_count + delta

    def _inner_has_node(self, node_id: NodeID) -> bool:
        return self.inner.has_node(node_id)

    def _inner_has_edge(self, edge_id: EdgeID) -> bool:
        return self.inner.has_edge(edge_id)

    def delete_by_prefix(self, prefix: str) -> Tuple[int, int]:
        self.flush_pending()
        return self.inner.delete_by_prefix(prefix)
