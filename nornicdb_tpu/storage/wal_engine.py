"""WAL engine decorator: log every mutation before applying it.

Reference: pkg/storage/wal_engine.go:56 ``NewWALEngine`` plus auto-compaction
snapshots (wired at pkg/nornicdb/db.go:899 ``EnableAutoCompaction``).

``DurableEngine`` composes ``MemoryEngine + WAL`` into the framework's
persistent store: on open it restores the newest snapshot and replays the
tail, giving Badger-equivalent durability semantics (crash recovery via
snapshot + WAL replay — reference pkg/nornicdb/db.go:838-858) with an
in-RAM working set.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional, Tuple

from nornicdb_tpu.errors import NornicError, WALCorruptionError
from nornicdb_tpu.storage.memory import MemoryEngine
from nornicdb_tpu.storage.types import Edge, EdgeID, Engine, EngineDecorator, Node, NodeID
from nornicdb_tpu.storage.wal import WAL, ReplayResult


def decode_op_args(op: str, data: Dict[str, Any]) -> tuple:
    """Decode a WAL/replication op payload into engine-call args — the one
    canonical copy of the op/data vocabulary (``apply_record`` and the
    replication layer both dispatch through it)."""
    if op in ("create_node", "update_node"):
        return (Node.from_dict(data),)
    if op in ("create_edge", "update_edge"):
        return (Edge.from_dict(data),)
    if op in ("delete_node", "delete_edge"):
        return (data["id"],)
    if op == "delete_by_prefix":
        return (data["prefix"],)
    raise ValueError(f"unknown replicated op {op}")


class WALEngine(EngineDecorator):
    """Applies each mutation to ``inner`` (which validates it), then appends
    it to the WAL, atomically under a mutation lock so the log order matches
    the applied order. The write is only acked to the caller after the WAL
    append, and for the production ``DurableEngine`` the inner engine is
    volatile RAM, so apply-before-log preserves the durability contract
    while guaranteeing a failed (invalid) mutation never poisons the log."""

    def __init__(
        self,
        inner: Engine,
        wal: WAL,
        auto_compact_every: int = 0,
    ):
        super().__init__(inner)
        self.wal = wal
        self.auto_compact_every = auto_compact_every
        self._since_compact = 0
        self._lock = threading.Lock()
        self._mut = threading.Lock()
        # replay fan-out hook (replication/read_fleet.py): a read
        # replica applies streamed WAL records at THIS engine, below the
        # Namespaced/Listenable layers, so mutation listeners — the
        # search-index feed, cache invalidation — never fire for
        # replicated writes. A replica sets ``on_applied(op, data)`` to
        # route every applied record into its own index/listener fan-out
        # (same add/update/delete paths a local write takes). None (the
        # default) keeps replay exactly as before; crash recovery runs
        # before the hook is installed.
        self.on_applied = None

    # -- replay plumbing -------------------------------------------------

    def apply_record(self, op: str, data: Dict[str, Any]) -> None:
        """Apply one WAL record to the inner engine (used during replay and
        by replication followers)."""
        try:
            # decode FIRST: it whitelists the op vocabulary (ValueError on
            # an unknown op), making the getattr dispatch safe
            args = decode_op_args(op, data)
            getattr(self.inner, op)(*args)
        except (KeyError, ValueError, NornicError):
            # replaying over a snapshot that already contains the mutation,
            # a delete of an already-deleted entity, or a record written by
            # a newer version with an op this build doesn't know —
            # idempotent, forward-compatible replay
            return
        cb = self.on_applied
        if cb is not None:
            try:
                cb(op, data)
            except Exception:  # noqa: BLE001 — fan-out must not poison replay
                pass

    def apply_and_log(self, op: str, data: Dict[str, Any],
                      seq: Optional[int] = None) -> int:
        """Idempotent replay apply PLUS a local WAL append, returning
        the appended seq. Read replicas (replication/read_fleet.py)
        apply streamed records through this so the replica's own WAL
        mirrors the primary's seq space record-for-record — ``seq``
        pins the PRIMARY's number (a replica joining mid-history sees
        its first record at the primary's post-compaction watermark,
        not 1): promotion then CONTINUES the numbering (surviving
        peers at watermark N accept the new primary's N+1 instead of
        dropping a restarted seq 1 as a duplicate), restarts resume
        from the true watermark, and a rejoining node can catch up
        from the promoted replica's log. Never used by crash recovery
        — ``recover()`` replays via ``apply_record``, which does not
        append."""
        self.apply_record(op, data)
        with self._mut:
            out = self.wal.append(op, data, seq=seq)
        self._maybe_compact()
        return out

    def recover(self) -> ReplayResult:
        """Restore snapshot state into inner, then replay the WAL tail.

        If snapshot files exist on disk but none is readable, recovery
        refuses to proceed: older segments were pruned at snapshot time,
        so replaying from seq 0 would silently open a near-empty store
        (reference analog: degraded mode, wal_degraded.go:6)."""
        state, snap_seq = self.wal.load_snapshot()
        if state is None and self.wal.has_snapshots():
            raise WALCorruptionError(
                "snapshot files exist but none is readable; refusing to "
                "recover from WAL tail alone (pre-snapshot segments were "
                "pruned). Restore a snapshot or remove snapshot files to "
                "force tail-only recovery."
            )
        if state is not None:
            self._load_state(state)
        return self.wal.replay(self.apply_record, from_seq=snap_seq)

    def _load_state(self, state: Dict[str, Any]) -> None:
        for nd in state.get("nodes", []):
            try:
                self.inner.create_node(Node.from_dict(nd))
            except Exception:
                pass
        for ed in state.get("edges", []):
            try:
                self.inner.create_edge(Edge.from_dict(ed))
            except Exception:
                pass

    def _dump_state(self) -> Dict[str, Any]:
        return {
            "nodes": [n.to_dict() for n in self.inner.all_nodes()],
            "edges": [e.to_dict() for e in self.inner.all_edges()],
        }

    def snapshot(self) -> str:
        """Write a full-state snapshot, pruning old segments.

        Holds the mutation lock across dump + seq stamp: without it, an
        append landing between ``_dump_state()`` and the snapshot's seq
        stamp gets pruned as "covered" while missing from the state —
        replay then silently loses it (caught by
        test_races_services.py::TestWALSnapshotVsAppend)."""
        with self._mut:
            return self.wal.write_snapshot(self._dump_state())

    def _maybe_compact(self) -> None:
        if self.auto_compact_every <= 0:
            return
        with self._lock:
            self._since_compact += 1
            if self._since_compact < self.auto_compact_every:
                return
            self._since_compact = 0
        self.snapshot()

    # -- mutations (apply-validates, then log; atomic so WAL order == applied order)

    def apply_op(
        self,
        op: str,
        data: Dict[str, Any],
        on_logged: Optional[Any] = None,
    ) -> int:
        """Apply one mutation by op name and return the WAL seq it was
        logged at, atomically under the mutation lock. ``on_logged(seq)``,
        if given, also runs under the lock — replication uses it to enqueue
        the record for streaming so enqueue order always matches seq order
        (two concurrent appliers can otherwise interleave between the
        engine call and the seq read, tagging both writes with the later
        seq and inverting stream order)."""
        args = decode_op_args(op, data)
        with self._mut:
            getattr(self.inner, op)(*args)
            seq = self.wal.append(op, data)
            if on_logged is not None:
                on_logged(seq)
        self._maybe_compact()
        return seq

    def create_node(self, node: Node) -> None:
        with self._mut:
            self.inner.create_node(node)
            self.wal.append("create_node", node.to_dict())
        self._maybe_compact()

    def update_node(self, node: Node) -> None:
        with self._mut:
            self.inner.update_node(node)
            self.wal.append("update_node", node.to_dict())
        self._maybe_compact()

    def delete_node(self, node_id: NodeID) -> None:
        with self._mut:
            self.inner.delete_node(node_id)
            self.wal.append("delete_node", {"id": node_id})
        self._maybe_compact()

    def create_edge(self, edge: Edge) -> None:
        with self._mut:
            self.inner.create_edge(edge)
            self.wal.append("create_edge", edge.to_dict())
        self._maybe_compact()

    def update_edge(self, edge: Edge) -> None:
        with self._mut:
            self.inner.update_edge(edge)
            self.wal.append("update_edge", edge.to_dict())
        self._maybe_compact()

    def delete_edge(self, edge_id: EdgeID) -> None:
        with self._mut:
            self.inner.delete_edge(edge_id)
            self.wal.append("delete_edge", {"id": edge_id})
        self._maybe_compact()

    def delete_by_prefix(self, prefix: str) -> Tuple[int, int]:
        with self._mut:
            result = self.inner.delete_by_prefix(prefix)
            self.wal.append("delete_by_prefix", {"prefix": prefix})
        return result

    def flush(self) -> None:
        self.wal.flush()
        self.inner.flush()

    def close(self) -> None:
        self.wal.close()
        self.inner.close()


class DurableEngine(WALEngine):
    """Persistent engine: RAM working set + WAL durability + snapshots.

    Opens (or creates) a data directory, restores the last snapshot, and
    replays the WAL tail. This is the framework's stand-in for the
    reference's BadgerEngine LSM store (pkg/storage/badger.go:70) — the
    durability contract (every acked mutation survives restart) is the
    same; the working set lives in RAM which suits the TPU design where
    hot data is columnarized onto the device anyway.
    """

    def __init__(
        self,
        data_dir: str,
        sync_every_write: bool = False,
        auto_compact_every: int = 50_000,
        max_segment_bytes: int = 16 * 1024 * 1024,
        encryptor=None,
    ):
        wal = WAL(
            data_dir,
            max_segment_bytes=max_segment_bytes,
            sync_every_write=sync_every_write,
            encryptor=encryptor,
        )
        super().__init__(MemoryEngine(), wal, auto_compact_every=auto_compact_every)
        self.replay_result: Optional[ReplayResult] = self.recover()

    def close(self) -> None:
        try:
            self.snapshot()
        except Exception:
            pass
        super().close()
