"""Transaction overlay engine: buffered writes with commit/rollback.

Reference: pkg/cypher/transaction.go + pkg/txsession/manager.go — explicit
BEGIN/COMMIT/ROLLBACK transactions. Writes land in an in-memory overlay
(read-your-writes), reads fall through to the inner engine, COMMIT
replays the op log onto the inner engine, ROLLBACK discards it. This is
the engine the Bolt and HTTP transaction endpoints run statements
against.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from nornicdb_tpu.errors import NotFoundError
from nornicdb_tpu.storage.types import Direction, Edge, EdgeID, Engine, Node, NodeID


class TransactionClosed(RuntimeError):
    pass


class TransactionOverlay(Engine):
    """One open transaction. Not thread-safe across statements by design —
    a tx belongs to one session (reference: txsession)."""

    def __init__(self, inner: Engine):
        self.inner = inner
        self._nodes: Dict[NodeID, Node] = {}       # created/updated in tx
        self._edges: Dict[EdgeID, Edge] = {}
        self._deleted_nodes: Set[NodeID] = set()
        self._deleted_edges: Set[EdgeID] = set()
        self._ops: List[Tuple[str, object]] = []   # replay log for commit
        self._open = True
        self.started_at = time.time()

    # -- lifecycle -------------------------------------------------------

    def _check_open(self) -> None:
        if not self._open:
            raise TransactionClosed("transaction already closed")

    def commit(self) -> int:
        """Replay buffered ops onto the inner engine. Returns op count."""
        self._check_open()
        self._open = False
        n = 0
        for op, arg in self._ops:
            if op == "create_node":
                self.inner.create_node(arg)  # type: ignore[arg-type]
            elif op == "update_node":
                self.inner.update_node(arg)  # type: ignore[arg-type]
            elif op == "delete_node":
                self.inner.delete_node(arg)  # type: ignore[arg-type]
            elif op == "create_edge":
                self.inner.create_edge(arg)  # type: ignore[arg-type]
            elif op == "update_edge":
                self.inner.update_edge(arg)  # type: ignore[arg-type]
            elif op == "delete_edge":
                self.inner.delete_edge(arg)  # type: ignore[arg-type]
            n += 1
        return n

    def rollback(self) -> int:
        self._check_open()
        self._open = False
        n = len(self._ops)
        self._ops.clear()
        self._nodes.clear()
        self._edges.clear()
        self._deleted_nodes.clear()
        self._deleted_edges.clear()
        return n

    @property
    def is_open(self) -> bool:
        return self._open

    # -- nodes -----------------------------------------------------------

    def create_node(self, node: Node) -> None:
        self._check_open()
        if self.has_node(node.id):
            raise ValueError(f"node exists: {node.id}")
        n = node.copy()
        from nornicdb_tpu.storage.types import now_ms

        ts = now_ms()
        n.created_at = n.created_at or ts
        n.updated_at = ts
        self._nodes[n.id] = n
        self._deleted_nodes.discard(n.id)
        self._ops.append(("create_node", n.copy()))

    def get_node(self, node_id: NodeID) -> Node:
        if node_id in self._deleted_nodes:
            raise NotFoundError(f"node {node_id} not found")
        n = self._nodes.get(node_id)
        if n is not None:
            return n.copy()
        return self.inner.get_node(node_id)

    def update_node(self, node: Node) -> None:
        self._check_open()
        old = self.get_node(node.id)  # raises if missing
        n = node.copy()
        from nornicdb_tpu.storage.types import now_ms

        n.created_at = old.created_at
        n.updated_at = now_ms()
        self._nodes[n.id] = n
        self._ops.append(("update_node", n.copy()))

    def delete_node(self, node_id: NodeID) -> None:
        self._check_open()
        self.get_node(node_id)  # raises if missing
        for e in self.get_node_edges(node_id, Direction.BOTH):
            self.delete_edge(e.id)
        self._nodes.pop(node_id, None)
        self._deleted_nodes.add(node_id)
        self._ops.append(("delete_node", node_id))

    def get_nodes_by_label(self, label: str) -> List[Node]:
        return [n for n in self.all_nodes() if label in n.labels]

    def all_nodes(self) -> Iterable[Node]:
        seen: Set[NodeID] = set()
        for n in self._nodes.values():
            seen.add(n.id)
            yield n.copy()
        for n in self.inner.all_nodes():
            if n.id not in seen and n.id not in self._deleted_nodes:
                yield n

    def batch_get_nodes(self, node_ids: Sequence[NodeID]) -> List[Optional[Node]]:
        out: List[Optional[Node]] = []
        for nid in node_ids:
            try:
                out.append(self.get_node(nid))
            except KeyError:
                out.append(None)
        return out

    def has_node(self, node_id: NodeID) -> bool:
        if node_id in self._deleted_nodes:
            return False
        return node_id in self._nodes or self.inner.has_node(node_id)

    # -- edges -----------------------------------------------------------

    def create_edge(self, edge: Edge) -> None:
        self._check_open()
        if self.has_edge(edge.id):
            raise ValueError(f"edge exists: {edge.id}")
        if not self.has_node(edge.start_node):
            raise NotFoundError(f"node {edge.start_node} not found")
        if not self.has_node(edge.end_node):
            raise NotFoundError(f"node {edge.end_node} not found")
        e = edge.copy()
        from nornicdb_tpu.storage.types import now_ms

        ts = now_ms()
        e.created_at = e.created_at or ts
        e.updated_at = ts
        self._edges[e.id] = e
        self._deleted_edges.discard(e.id)
        self._ops.append(("create_edge", e.copy()))

    def get_edge(self, edge_id: EdgeID) -> Edge:
        if edge_id in self._deleted_edges:
            raise NotFoundError(f"edge {edge_id} not found")
        e = self._edges.get(edge_id)
        if e is not None:
            return e.copy()
        return self.inner.get_edge(edge_id)

    def update_edge(self, edge: Edge) -> None:
        self._check_open()
        old = self.get_edge(edge.id)
        e = edge.copy()
        from nornicdb_tpu.storage.types import now_ms

        e.created_at = old.created_at
        e.updated_at = now_ms()
        # endpoints/type immutable (parity with engines)
        e.start_node, e.end_node, e.type = old.start_node, old.end_node, old.type
        self._edges[e.id] = e
        self._ops.append(("update_edge", e.copy()))

    def delete_edge(self, edge_id: EdgeID) -> None:
        self._check_open()
        self.get_edge(edge_id)
        self._edges.pop(edge_id, None)
        self._deleted_edges.add(edge_id)
        self._ops.append(("delete_edge", edge_id))

    def get_edges_by_type(self, edge_type: str) -> List[Edge]:
        return [e for e in self.all_edges() if e.type == edge_type]

    def all_edges(self) -> Iterable[Edge]:
        seen: Set[EdgeID] = set()
        for e in self._edges.values():
            seen.add(e.id)
            yield e.copy()
        for e in self.inner.all_edges():
            if e.id not in seen and e.id not in self._deleted_edges:
                yield e

    def get_node_edges(self, node_id: NodeID, direction: str = Direction.BOTH) -> List[Edge]:
        out = []
        for e in self.all_edges():
            if direction in (Direction.OUTGOING, Direction.BOTH) and e.start_node == node_id:
                out.append(e)
            elif direction in (Direction.INCOMING, Direction.BOTH) and e.end_node == node_id:
                out.append(e)
        return out

    def has_edge(self, edge_id: EdgeID) -> bool:
        if edge_id in self._deleted_edges:
            return False
        return edge_id in self._edges or self.inner.has_edge(edge_id)

    # -- counts ----------------------------------------------------------

    def count_nodes(self) -> int:
        return sum(1 for _ in self.all_nodes())

    def count_edges(self) -> int:
        return sum(1 for _ in self.all_edges())


class TransactionManager:
    """Tracks open transactions per session with timeout reaping
    (reference: pkg/txsession/manager.go:138)."""

    def __init__(self, timeout_seconds: float = 60.0):
        self._txs: Dict[str, TransactionOverlay] = {}
        self._lock = threading.Lock()
        self.timeout = timeout_seconds

    def begin(self, session_id: str, storage: Engine) -> TransactionOverlay:
        with self._lock:
            existing = self._txs.get(session_id)
            if existing is not None and existing.is_open:
                raise RuntimeError("transaction already open for session")
            tx = TransactionOverlay(storage)
            self._txs[session_id] = tx
            return tx

    def get(self, session_id: str) -> Optional[TransactionOverlay]:
        with self._lock:
            tx = self._txs.get(session_id)
            return tx if tx is not None and tx.is_open else None

    def commit(self, session_id: str) -> int:
        tx = self.get(session_id)
        if tx is None:
            raise RuntimeError("no open transaction")
        try:
            return tx.commit()
        finally:
            self._drop(session_id)

    def rollback(self, session_id: str) -> int:
        tx = self.get(session_id)
        if tx is None:
            raise RuntimeError("no open transaction")
        try:
            return tx.rollback()
        finally:
            self._drop(session_id)

    def _drop(self, session_id: str) -> None:
        with self._lock:
            self._txs.pop(session_id, None)

    def reap_expired(self) -> int:
        now = time.time()
        reaped = 0
        with self._lock:
            for sid, tx in list(self._txs.items()):
                if tx.is_open and now - tx.started_at > self.timeout:
                    tx.rollback()
                    del self._txs[sid]
                    reaped += 1
        return reaped
