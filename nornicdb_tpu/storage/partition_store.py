"""Disk partition store: the cold tier of the tiered vector ladder.

The tiered serving plane (``search/tiered_store.py``) keeps only a
bounded set of partitions device-resident; every partition's payload —
its brute slot ids, external ids, PQ codes and float32 rows — spills
here at build time as one ``.npz`` file per partition. Background
promotion reads a partition back to fill a device slab; the exact cold
side-scan reads rows when a query probes a partition that is neither
device- nor host-resident.

Writes are atomic (tmp file + ``os.replace``) so a crashed build can
never leave a torn partition behind, and every read validates the key
set — a missing or malformed file returns ``None`` and the caller
degrades through the freshness ladder (tiered -> quant -> f32 -> host),
never answers from garbage.
"""

from __future__ import annotations

import os
import shutil
import tempfile
import threading
from typing import Any, Dict, List, Optional

import numpy as np

_KEYS = ("slots", "ext_ids", "rows", "codes")


class PartitionStore:
    """One directory of per-partition ``part_<pid>.npz`` files.

    Thread-safe: the build thread writes whole partitions while the
    background pager reads others; a per-store lock serializes the
    directory-level bookkeeping (file create/replace/delete), while the
    payload serialization itself runs outside it.
    """

    def __init__(self, root_dir: Optional[str] = None):
        if root_dir is None:
            root_dir = tempfile.mkdtemp(prefix="nornic_tiered_")
            self._owns_dir = True
        else:
            os.makedirs(root_dir, exist_ok=True)
            self._owns_dir = False
        self.root_dir = root_dir
        self._lock = threading.Lock()

    def _path(self, pid: int) -> str:
        return os.path.join(self.root_dir, f"part_{int(pid)}.npz")

    # -- write ------------------------------------------------------------

    def save_partition(
        self,
        pid: int,
        slots: np.ndarray,
        ext_ids: List[str],
        rows: np.ndarray,
        codes: np.ndarray,
    ) -> None:
        """Persist one partition atomically (tmp + rename)."""
        payload = {
            "slots": np.asarray(slots, dtype=np.int64),
            "ext_ids": np.asarray(ext_ids),
            "rows": np.asarray(rows, dtype=np.float32),
            "codes": np.asarray(codes, dtype=np.uint8),
        }
        fd, tmp = tempfile.mkstemp(
            prefix=f"part_{int(pid)}.", suffix=".tmp", dir=self.root_dir)
        try:
            with os.fdopen(fd, "wb") as f:
                np.savez_compressed(f, **payload)
            with self._lock:
                os.replace(tmp, self._path(pid))
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    # -- read -------------------------------------------------------------

    def load_partition(self, pid: int) -> Optional[Dict[str, Any]]:
        """Partition payload dict, or None when missing/torn (the
        caller degrades down the ladder instead of crashing)."""
        path = self._path(pid)
        try:
            with np.load(path, allow_pickle=False) as data:
                if any(k not in data for k in _KEYS):
                    return None
                return {
                    "slots": np.asarray(data["slots"], dtype=np.int64),
                    "ext_ids": [str(e) for e in data["ext_ids"]],
                    "rows": np.asarray(data["rows"], dtype=np.float32),
                    "codes": np.asarray(data["codes"], dtype=np.uint8),
                }
        except (OSError, ValueError, KeyError):
            return None

    def has_partition(self, pid: int) -> bool:
        return os.path.exists(self._path(pid))

    def partition_ids(self) -> List[int]:
        out: List[int] = []
        try:
            names = os.listdir(self.root_dir)
        except OSError:
            return out
        for name in names:
            if name.startswith("part_") and name.endswith(".npz"):
                try:
                    out.append(int(name[len("part_"):-len(".npz")]))
                except ValueError:
                    continue
        return sorted(out)

    # -- bookkeeping ------------------------------------------------------

    def delete_partition(self, pid: int) -> bool:
        with self._lock:
            try:
                os.unlink(self._path(pid))
                return True
            except OSError:
                return False

    def clear(self) -> None:
        for pid in self.partition_ids():
            self.delete_partition(pid)

    def disk_bytes(self) -> int:
        """Total on-disk payload bytes — the cold-tier footprint the
        resource gauges report next to device/host bytes."""
        total = 0
        try:
            names = os.listdir(self.root_dir)
        except OSError:
            return 0
        for name in names:
            if name.startswith("part_") and name.endswith(".npz"):
                try:
                    total += os.path.getsize(
                        os.path.join(self.root_dir, name))
                except OSError:
                    continue
        return total

    def close(self) -> None:
        """Drop the spill directory when this store created it."""
        if self._owns_dir:
            shutil.rmtree(self.root_dir, ignore_errors=True)
