"""In-memory storage engine — the universal test fixture.

Reference: pkg/storage/memory.go:63 ``NewMemoryEngine``. Maintains label and
edge-type secondary indexes plus per-node adjacency for O(1) degree queries.
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from nornicdb_tpu.errors import AlreadyExistsError, NotFoundError
from nornicdb_tpu.storage.types import (
    Direction,
    Edge,
    EdgeID,
    Engine,
    Node,
    NodeID,
    now_ms,
)


class MemoryEngine(Engine):
    def __init__(self):
        self._lock = threading.RLock()
        self._nodes: Dict[NodeID, Node] = {}
        self._edges: Dict[EdgeID, Edge] = {}
        self._by_label: Dict[str, Set[NodeID]] = {}
        self._by_type: Dict[str, Set[EdgeID]] = {}
        self._out: Dict[NodeID, Set[EdgeID]] = {}
        self._in: Dict[NodeID, Set[EdgeID]] = {}

    # -- nodes ----------------------------------------------------------

    def create_node(self, node: Node) -> None:
        with self._lock:
            if node.id in self._nodes:
                raise AlreadyExistsError(f"node {node.id} already exists")
            n = node.copy()
            if not n.created_at:
                n.created_at = now_ms()
            if not n.updated_at:
                n.updated_at = n.created_at
            self._nodes[n.id] = n
            for label in n.labels:
                self._by_label.setdefault(label, set()).add(n.id)

    def get_node(self, node_id: NodeID) -> Node:
        with self._lock:
            n = self._nodes.get(node_id)
            if n is None:
                raise NotFoundError(f"node {node_id} not found")
            return n.copy()

    def update_node(self, node: Node) -> None:
        with self._lock:
            old = self._nodes.get(node.id)
            if old is None:
                raise NotFoundError(f"node {node.id} not found")
            n = node.copy()
            n.created_at = old.created_at
            n.updated_at = now_ms()
            for label in old.labels:
                if label not in n.labels:
                    self._by_label.get(label, set()).discard(n.id)
            for label in n.labels:
                self._by_label.setdefault(label, set()).add(n.id)
            self._nodes[n.id] = n

    def delete_node(self, node_id: NodeID) -> None:
        with self._lock:
            n = self._nodes.get(node_id)
            if n is None:
                raise NotFoundError(f"node {node_id} not found")
            for eid in list(self._out.get(node_id, ())) + list(
                self._in.get(node_id, ())
            ):
                if eid in self._edges:
                    self._delete_edge_locked(eid)
            del self._nodes[node_id]
            for label in n.labels:
                self._by_label.get(label, set()).discard(node_id)
            self._out.pop(node_id, None)
            self._in.pop(node_id, None)

    def get_nodes_by_label(self, label: str) -> List[Node]:
        with self._lock:
            ids = self._by_label.get(label, set())
            return [self._nodes[i].copy() for i in ids if i in self._nodes]

    def count_nodes_by_label(self, label: str) -> int:
        """Label cardinality without materializing nodes (EXPLAIN
        row estimates probe this optionally)."""
        with self._lock:
            return len(self._by_label.get(label, ()))

    def node_ids_by_label(self, label: str) -> List[NodeID]:
        with self._lock:
            ids = self._by_label.get(label, set())
            return [i for i in ids if i in self._nodes]

    def all_nodes(self) -> Iterable[Node]:
        with self._lock:
            return [n.copy() for n in self._nodes.values()]

    def batch_get_nodes(self, node_ids: Sequence[NodeID]) -> List[Optional[Node]]:
        with self._lock:
            return [
                self._nodes[i].copy() if i in self._nodes else None for i in node_ids
            ]

    # -- edges ----------------------------------------------------------

    def create_edge(self, edge: Edge) -> None:
        with self._lock:
            if edge.id in self._edges:
                raise AlreadyExistsError(f"edge {edge.id} already exists")
            if edge.start_node not in self._nodes:
                raise NotFoundError(f"start node {edge.start_node} not found")
            if edge.end_node not in self._nodes:
                raise NotFoundError(f"end node {edge.end_node} not found")
            e = edge.copy()
            if not e.created_at:
                e.created_at = now_ms()
            if not e.updated_at:
                e.updated_at = e.created_at
            self._edges[e.id] = e
            self._by_type.setdefault(e.type, set()).add(e.id)
            self._out.setdefault(e.start_node, set()).add(e.id)
            self._in.setdefault(e.end_node, set()).add(e.id)

    def get_edge(self, edge_id: EdgeID) -> Edge:
        with self._lock:
            e = self._edges.get(edge_id)
            if e is None:
                raise NotFoundError(f"edge {edge_id} not found")
            return e.copy()

    def update_edge(self, edge: Edge) -> None:
        with self._lock:
            old = self._edges.get(edge.id)
            if old is None:
                raise NotFoundError(f"edge {edge.id} not found")
            e = edge.copy()
            e.created_at = old.created_at
            e.updated_at = now_ms()
            # endpoints/type are immutable in the reference; enforce same
            e.start_node, e.end_node, e.type = (
                old.start_node,
                old.end_node,
                old.type,
            )
            self._edges[e.id] = e

    def _delete_edge_locked(self, edge_id: EdgeID) -> None:
        e = self._edges.pop(edge_id)
        self._by_type.get(e.type, set()).discard(edge_id)
        self._out.get(e.start_node, set()).discard(edge_id)
        self._in.get(e.end_node, set()).discard(edge_id)

    def delete_edge(self, edge_id: EdgeID) -> None:
        with self._lock:
            if edge_id not in self._edges:
                raise NotFoundError(f"edge {edge_id} not found")
            self._delete_edge_locked(edge_id)

    def get_edges_by_type(self, edge_type: str) -> List[Edge]:
        with self._lock:
            ids = self._by_type.get(edge_type, set())
            return [self._edges[i].copy() for i in ids if i in self._edges]

    def all_edges(self) -> Iterable[Edge]:
        with self._lock:
            return [e.copy() for e in self._edges.values()]

    def get_node_edges(
        self, node_id: NodeID, direction: str = Direction.BOTH
    ) -> List[Edge]:
        with self._lock:
            ids: Set[EdgeID] = set()
            if direction in (Direction.OUTGOING, Direction.BOTH):
                ids |= self._out.get(node_id, set())
            if direction in (Direction.INCOMING, Direction.BOTH):
                ids |= self._in.get(node_id, set())
            return [self._edges[i].copy() for i in ids if i in self._edges]

    def degree(self, node_id: NodeID, direction: str = Direction.BOTH) -> int:
        with self._lock:
            if direction == Direction.OUTGOING:
                return len(self._out.get(node_id, ()))
            if direction == Direction.INCOMING:
                return len(self._in.get(node_id, ()))
            return len(
                self._out.get(node_id, set()) | self._in.get(node_id, set())
            )

    # -- counts ---------------------------------------------------------

    def count_nodes(self) -> int:
        with self._lock:
            return len(self._nodes)

    def count_edges(self) -> int:
        with self._lock:
            return len(self._edges)

    def has_node(self, node_id: NodeID) -> bool:
        with self._lock:
            return node_id in self._nodes

    def has_edge(self, edge_id: EdgeID) -> bool:
        with self._lock:
            return edge_id in self._edges

    def count_nodes_with_prefix(self, prefix: str) -> int:
        """Reference: PrefixStatsEngine (types.go:432)."""
        with self._lock:
            return sum(1 for i in self._nodes if i.startswith(prefix))

    def count_edges_with_prefix(self, prefix: str) -> int:
        with self._lock:
            return sum(1 for i in self._edges if i.startswith(prefix))

    def delete_by_prefix(self, prefix: str) -> Tuple[int, int]:
        with self._lock:
            return super().delete_by_prefix(prefix)

    def clear(self) -> None:
        with self._lock:
            self._nodes.clear()
            self._edges.clear()
            self._by_label.clear()
            self._by_type.clear()
            self._out.clear()
            self._in.clear()
