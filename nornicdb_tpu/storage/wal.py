"""Write-ahead log: segmented, checksummed, with snapshots and tail repair.

Re-expresses the reference WAL (pkg/storage/wal.go:282 ``WAL``, ``NewWAL``
:334, ``Snapshot`` :1021, ``ReplayResult`` :1826) and tail repair
(pkg/storage/wal_repair.go:25 ``repairWALTailIfNeeded``).

Record framing:  ``uint32 payload_len | uint32 crc32(payload) | payload``
Payload is msgpack (falls back to JSON if msgpack is unavailable).
A torn/corrupt tail record truncates the segment at the last valid record
instead of failing recovery; corruption mid-segment stops replay there and
reports it (degraded mode, reference wal_degraded.go:6).
"""

from __future__ import annotations

import os
import struct
import threading
import time
import zlib
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from nornicdb_tpu.obs import REGISTRY

_APPEND_H = REGISTRY.histogram(
    "nornicdb_wal_append_seconds",
    "WAL record append latency (encode + write [+ fsync when "
    "sync_every_write])")
_FSYNC_H = REGISTRY.histogram(
    "nornicdb_wal_fsync_seconds", "WAL fsync latency")

def _typed_default(v):
    # temporal/duration/point property values serialize as tagged maps
    # (query/temporal_types.py codec)
    from nornicdb_tpu.query.temporal_types import encode_value

    return encode_value(v)


def _typed_hook(m):
    from nornicdb_tpu.query.temporal_types import decode_map

    return decode_map(m)


try:
    import msgpack  # ships with flax

    def _pack(obj) -> bytes:
        return msgpack.packb(obj, use_bin_type=True, default=_typed_default)

    def _unpack(b: bytes):
        return msgpack.unpackb(b, raw=False, strict_map_key=False,
                               object_hook=_typed_hook)

except ImportError:  # pragma: no cover
    import json

    def _pack(obj) -> bytes:
        return json.dumps(obj, default=_typed_default).encode("utf-8")

    def _unpack(b: bytes):
        from nornicdb_tpu.query.temporal_types import decode_tree

        return decode_tree(json.loads(b.decode("utf-8")))


_HEADER = struct.Struct("<II")  # payload_len, crc32
SEGMENT_PREFIX = "wal-"
SEGMENT_SUFFIX = ".log"
SNAPSHOT_PREFIX = "snapshot-"
_ENC_MAGIC = b"NKE1"
SNAPSHOT_SUFFIX = ".bin"


@dataclass
class ReplayResult:
    records_applied: int = 0
    segments_read: int = 0
    snapshot_seq: int = 0
    last_seq: int = 0
    torn_tail_repaired: bool = False
    corrupt_segments: List[str] = field(default_factory=list)
    degraded: bool = False


class WAL:
    """Segmented append-only log. Thread-safe."""

    def __init__(
        self,
        directory: str,
        max_segment_bytes: int = 16 * 1024 * 1024,
        sync_every_write: bool = False,
        retained_segments: int = 4,
        encryptor=None,
    ):
        self.dir = directory
        self._enc = encryptor
        self.max_segment_bytes = max_segment_bytes
        self.sync_every_write = sync_every_write
        self.retained_segments = retained_segments
        self._lock = threading.Lock()
        self._seq = 0
        self._fh = None
        self._fh_path: Optional[str] = None
        self._fh_size = 0
        os.makedirs(self.dir, exist_ok=True)
        self._seq = self._scan_last_seq()

    # -- payload codec (optional AES-256-GCM at rest; reference wires
    # at-rest encryption into the storage layer at db.go:776-805) -------

    def _encode(self, obj) -> bytes:
        payload = _pack(obj)
        if self._enc is not None:
            payload = _ENC_MAGIC + self._enc.encrypt(payload)
        return payload

    def _decode(self, payload: bytes):
        if payload[: len(_ENC_MAGIC)] == _ENC_MAGIC:
            if self._enc is None:
                from nornicdb_tpu.encryption import EncryptionError

                raise EncryptionError(
                    "WAL is encrypted; open with the passphrase"
                )
            payload = self._enc.decrypt(payload[len(_ENC_MAGIC):])
        return _unpack(payload)

    # -- segment bookkeeping --------------------------------------------

    def _segment_paths(self) -> List[str]:
        out = []
        for name in os.listdir(self.dir):
            if name.startswith(SEGMENT_PREFIX) and name.endswith(SEGMENT_SUFFIX):
                out.append(os.path.join(self.dir, name))
        out.sort(key=lambda p: self._segment_start_seq(p))
        return out

    @staticmethod
    def _segment_start_seq(path: str) -> int:
        base = os.path.basename(path)
        return int(base[len(SEGMENT_PREFIX) : -len(SEGMENT_SUFFIX)])

    def _snapshot_paths(self) -> List[str]:
        out = []
        for name in os.listdir(self.dir):
            if name.startswith(SNAPSHOT_PREFIX) and name.endswith(SNAPSHOT_SUFFIX):
                out.append(os.path.join(self.dir, name))
        out.sort(key=lambda p: self._snapshot_seq(p))
        return out

    @staticmethod
    def _snapshot_seq(path: str) -> int:
        base = os.path.basename(path)
        return int(base[len(SNAPSHOT_PREFIX) : -len(SNAPSHOT_SUFFIX)])

    def _scan_last_seq(self) -> int:
        """Find the last sequence number. Sequences are monotone across
        segments, so only the newest segment needs decoding; older segments'
        coverage is derivable from filenames (start seqs)."""
        last = 0
        snaps = self._snapshot_paths()
        if snaps:
            last = self._snapshot_seq(snaps[-1])
        segs = self._segment_paths()
        if segs:
            last = max(last, self._segment_start_seq(segs[-1]))
            for rec, _ in _iter_records(segs[-1]):
                seq = rec.get("seq", 0)
                if seq > last:
                    last = seq
        return last

    def has_snapshots(self) -> bool:
        return bool(self._snapshot_paths())

    # -- append ---------------------------------------------------------

    @property
    def last_seq(self) -> int:
        with self._lock:
            return self._seq

    def append(self, op: str, data: Dict[str, Any],
               seq: Optional[int] = None) -> int:
        """Append one record; returns its sequence number. ``seq``
        pins the record to an EXTERNAL sequence number instead of the
        local counter — read replicas (replication/read_fleet.py) log
        streamed records under the primary's numbering so their WAL
        stays seq-aligned even when they join mid-history (the
        primary's pre-snapshot segments are pruned, so the first
        shipped record may be seq 50001, not 1). The counter jumps
        forward to the pinned seq; a pinned seq at or below the
        counter is a replay overlap and appends under the counter as
        usual."""
        t0 = time.perf_counter()
        with self._lock:
            if seq is not None and seq > self._seq:
                self._seq = seq
            else:
                self._seq += 1
            # the primary append timestamp rides every record so a
            # replica can observe per-record replication latency in
            # SECONDS (nornicdb_replication_apply_delay_seconds,
            # ISSUE 13) — wal_sync catch-ups ship it alongside seq.
            # Replay ignores unknown keys, so old logs stay readable.
            rec = {"seq": self._seq, "op": op, "data": data,
                   "ts": round(time.time(), 6)}
            payload = self._encode(rec)
            frame = _HEADER.pack(len(payload), zlib.crc32(payload)) + payload
            self._ensure_segment_locked(len(frame))
            self._fh.write(frame)
            self._fh_size += len(frame)
            if self.sync_every_write:
                self._fh.flush()
                ts = time.perf_counter()
                os.fsync(self._fh.fileno())
                _FSYNC_H.observe(time.perf_counter() - ts)
            seq = self._seq
        _APPEND_H.observe(time.perf_counter() - t0)
        return seq

    def earliest_retained_seq(self) -> int:
        """Lowest watermark the segment files can serve a COMPLETE
        record stream from: ``iter_records(from_seq=N)`` misses pruned
        history iff ``N < earliest_retained_seq()``. Snapshot pruning
        keeps ``retained_segments`` pre-snapshot segments, so a
        routinely-lagging standby inside that window catches up from
        records; only a standby behind the retention horizon needs the
        snapshot."""
        with self._lock:
            segs = self._segment_paths()
            if segs:
                # a segment named with start seq S holds records > S
                return self._segment_start_seq(segs[0])
            return self._seq

    def advance_seq(self, seq: int) -> None:
        """Jump the sequence counter forward (never backward) without
        writing a record. A read replica bootstrapping from a shipped
        primary snapshot uses this so its counter lands on the
        snapshot's seq — the streamed tail then appends under the
        primary's numbering with no gap."""
        with self._lock:
            if seq > self._seq:
                self._seq = seq

    def _ensure_segment_locked(self, incoming: int) -> None:
        if self._fh is not None and self._fh_size + incoming <= self.max_segment_bytes:
            return
        if self._fh is not None:
            self._fh.flush()
            self._fh.close()
        start = self._seq
        path = os.path.join(self.dir, f"{SEGMENT_PREFIX}{start}{SEGMENT_SUFFIX}")
        self._fh = open(path, "ab")
        self._fh_path = path
        self._fh_size = os.path.getsize(path)

    def flush(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.flush()
                ts = time.perf_counter()
                os.fsync(self._fh.fileno())
                _FSYNC_H.observe(time.perf_counter() - ts)

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.flush()
                self._fh.close()
                self._fh = None

    # -- snapshot / retention -------------------------------------------

    def write_snapshot(self, state: Dict[str, Any]) -> str:
        """Atomically persist a full-state snapshot at the current seq and
        prune old segments/snapshots (reference: wal.go:1021 Snapshot +
        segment retention)."""
        with self._lock:
            seq = self._seq
            payload = self._encode({"seq": seq, "state": state})
            path = os.path.join(self.dir, f"{SNAPSHOT_PREFIX}{seq}{SNAPSHOT_SUFFIX}")
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(_HEADER.pack(len(payload), zlib.crc32(payload)))
                f.write(payload)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            self._prune_locked(seq)
            return path

    def _prune_locked(self, snapshot_seq: int) -> None:
        # drop all snapshots except the newest
        snaps = self._snapshot_paths()
        for p in snaps[:-1]:
            try:
                os.remove(p)
            except OSError:
                pass
        # drop fully-covered segments beyond the retention window. A
        # segment's records all have seq <= the next segment's start seq
        # (filenames carry start seqs), so coverage needs no decoding.
        segs = self._segment_paths()
        covered = []
        for i, p in enumerate(segs):
            if i + 1 < len(segs):
                seg_last = self._segment_start_seq(segs[i + 1])
            else:
                seg_last = self._seq
            if seg_last <= snapshot_seq:
                covered.append(p)
        for p in covered[: max(0, len(covered) - self.retained_segments)]:
            if p == self._fh_path:
                continue
            try:
                os.remove(p)
            except OSError:
                pass

    # -- replay ---------------------------------------------------------

    def load_snapshot(self) -> Tuple[Optional[Dict[str, Any]], int]:
        """Return (state, seq) of the newest valid snapshot, or (None, 0)."""
        for path in reversed(self._snapshot_paths()):
            try:
                with open(path, "rb") as f:
                    head = f.read(_HEADER.size)
                    if len(head) < _HEADER.size:
                        continue
                    ln, crc = _HEADER.unpack(head)
                    payload = f.read(ln)
                    if len(payload) != ln or zlib.crc32(payload) != crc:
                        continue
                    doc = self._decode(payload)
                    return doc["state"], doc["seq"]
            except (OSError, ValueError, KeyError):
                continue
        return None, 0

    def iter_records(self, from_seq: int = 0) -> List[Dict[str, Any]]:
        """Return raw WAL records (seq included) with seq > from_seq, in
        log order. Read-only: no tail repair, no degraded-mode side
        effects — replication catch-up uses this to ship seq-tagged
        history. Materialized under the lock: a lazy generator would race
        auto-compaction's segment pruning, silently shipping a gapped
        history to the standby."""
        out: List[Dict[str, Any]] = []
        with self._lock:
            for path in self._segment_paths():
                for rec, _ in _iter_records(path, self._decode):
                    if rec.get("seq", 0) > from_seq:
                        out.append(rec)
        return out

    def replay(
        self, apply: Callable[[str, Dict[str, Any]], None], from_seq: int = 0
    ) -> ReplayResult:
        """Apply every record with seq > from_seq, repairing a torn tail on
        the newest segment and flagging mid-log corruption as degraded."""
        res = ReplayResult(snapshot_seq=from_seq, last_seq=from_seq)
        with self._lock:
            segs = self._segment_paths()
            for i, path in enumerate(segs):
                is_tail_segment = i == len(segs) - 1
                res.segments_read += 1
                good_bytes = 0
                corrupt = False
                for rec, end_off in _iter_records(path, self._decode):
                    good_bytes = end_off
                    seq = rec.get("seq", 0)
                    if seq > from_seq:
                        apply(rec["op"], rec.get("data", {}))
                        res.records_applied += 1
                        res.last_seq = max(res.last_seq, seq)
                size = os.path.getsize(path)
                if good_bytes < size:
                    corrupt = True
                if corrupt:
                    if is_tail_segment:
                        # torn tail: truncate to last valid record
                        with open(path, "ab") as f:
                            f.truncate(good_bytes)
                        res.torn_tail_repaired = True
                    else:
                        res.corrupt_segments.append(path)
                        res.degraded = True
            if res.last_seq > self._seq:
                self._seq = res.last_seq
        return res


def _iter_records(path: str, decode=None):
    """Yield (record, end_offset) for each valid record; stop at the first
    corrupt/torn frame. A wrong or missing encryption passphrase raises
    instead of masquerading as a torn log."""
    if decode is None:
        decode = _unpack
    try:
        with open(path, "rb") as f:
            off = 0
            while True:
                head = f.read(_HEADER.size)
                if len(head) < _HEADER.size:
                    return
                ln, crc = _HEADER.unpack(head)
                if ln > 256 * 1024 * 1024:  # insane length => corrupt header
                    return
                payload = f.read(ln)
                if len(payload) != ln or zlib.crc32(payload) != crc:
                    return
                off += _HEADER.size + ln
                try:
                    rec = decode(payload)
                except Exception as exc:
                    from nornicdb_tpu.encryption import EncryptionError

                    if isinstance(exc, EncryptionError):
                        raise
                    return
                if not isinstance(rec, dict) or "op" not in rec:
                    return
                yield rec, off
    except OSError:
        return
