"""Namespaced engine decorator — multi-database on one store.

Prefixes every node/edge ID with ``dbname:`` on the way in and strips it on
the way out, so one physical store hosts many logical databases.
Reference: pkg/storage/namespaced.go:57 ``NewNamespacedEngine``.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Sequence, Tuple

from nornicdb_tpu.errors import NotFoundError
from nornicdb_tpu.storage.types import (
    Direction,
    Edge,
    EdgeID,
    Engine,
    EngineDecorator,
    Node,
    NodeID,
)

DEFAULT_DB = "neo4j"


class NamespacedEngine(EngineDecorator):
    def __init__(self, inner: Engine, database: str = DEFAULT_DB):
        super().__init__(inner)
        self.database = database
        self._prefix = database + ":"

    # -- id mapping -----------------------------------------------------

    def _q(self, raw_id: str) -> str:
        """Qualify a logical ID with the namespace prefix. Always prepends:
        a user ID that happens to start with "<db>:" must not alias onto a
        different node's physical key."""
        return self._prefix + raw_id

    def _unq(self, qual_id: str) -> str:
        if qual_id.startswith(self._prefix):
            return qual_id[len(self._prefix) :]
        return qual_id

    def _node_in(self, node: Node) -> Node:
        n = node.copy()
        n.id = self._q(n.id)
        return n

    def _node_out(self, node: Node) -> Node:
        node.id = self._unq(node.id)
        return node

    def _edge_in(self, edge: Edge) -> Edge:
        e = edge.copy()
        e.id = self._q(e.id)
        e.start_node = self._q(e.start_node)
        e.end_node = self._q(e.end_node)
        return e

    def _edge_out(self, edge: Edge) -> Edge:
        edge.id = self._unq(edge.id)
        edge.start_node = self._unq(edge.start_node)
        edge.end_node = self._unq(edge.end_node)
        return edge

    def _mine(self, qual_id: str) -> bool:
        return qual_id.startswith(self._prefix)

    # -- nodes ----------------------------------------------------------

    def create_node(self, node: Node) -> None:
        self.inner.create_node(self._node_in(node))

    def get_node(self, node_id: NodeID) -> Node:
        try:
            return self._node_out(self.inner.get_node(self._q(node_id)))
        except NotFoundError:
            raise NotFoundError(f"node {node_id} not found") from None

    def update_node(self, node: Node) -> None:
        self.inner.update_node(self._node_in(node))

    def delete_node(self, node_id: NodeID) -> None:
        try:
            self.inner.delete_node(self._q(node_id))
        except NotFoundError:
            raise NotFoundError(f"node {node_id} not found") from None

    def has_node(self, node_id: NodeID) -> bool:
        return self.inner.has_node(self._q(node_id))

    def has_edge(self, edge_id: EdgeID) -> bool:
        return self.inner.has_edge(self._q(edge_id))

    def get_nodes_by_label(self, label: str) -> List[Node]:
        return [
            self._node_out(n)
            for n in self.inner.get_nodes_by_label(label)
            if self._mine(n.id)
        ]

    def node_ids_by_label(self, label: str) -> List[NodeID]:
        # inlined strip/filter: this is the hot path of paged label
        # listings (GraphQL nodes(label:)), where per-id method calls
        # dominated the request
        p = self._prefix
        lp = len(p)
        return [i[lp:] for i in self.inner.node_ids_by_label(label)
                if i.startswith(p)]

    def all_nodes(self) -> Iterable[Node]:
        return [self._node_out(n) for n in self.inner.all_nodes() if self._mine(n.id)]

    def batch_get_nodes(self, node_ids: Sequence[NodeID]) -> List[Optional[Node]]:
        got = self.inner.batch_get_nodes([self._q(i) for i in node_ids])
        return [self._node_out(n) if n is not None else None for n in got]

    # -- edges ----------------------------------------------------------

    def create_edge(self, edge: Edge) -> None:
        self.inner.create_edge(self._edge_in(edge))

    def get_edge(self, edge_id: EdgeID) -> Edge:
        try:
            return self._edge_out(self.inner.get_edge(self._q(edge_id)))
        except NotFoundError:
            raise NotFoundError(f"edge {edge_id} not found") from None

    def update_edge(self, edge: Edge) -> None:
        self.inner.update_edge(self._edge_in(edge))

    def delete_edge(self, edge_id: EdgeID) -> None:
        try:
            self.inner.delete_edge(self._q(edge_id))
        except NotFoundError:
            raise NotFoundError(f"edge {edge_id} not found") from None

    def get_edges_by_type(self, edge_type: str) -> List[Edge]:
        return [
            self._edge_out(e)
            for e in self.inner.get_edges_by_type(edge_type)
            if self._mine(e.id)
        ]

    def all_edges(self) -> Iterable[Edge]:
        return [self._edge_out(e) for e in self.inner.all_edges() if self._mine(e.id)]

    def get_node_edges(
        self, node_id: NodeID, direction: str = Direction.BOTH
    ) -> List[Edge]:
        return [
            self._edge_out(e)
            for e in self.inner.get_node_edges(self._q(node_id), direction)
        ]

    def degree(self, node_id: NodeID, direction: str = Direction.BOTH) -> int:
        return self.inner.degree(self._q(node_id), direction)

    # -- counts scoped to this namespace --------------------------------

    def count_nodes(self) -> int:
        counter = getattr(self.inner, "count_nodes_with_prefix", None)
        if counter is not None:
            return counter(self._prefix)
        return sum(1 for n in self.inner.all_nodes() if self._mine(n.id))

    def count_edges(self) -> int:
        counter = getattr(self.inner, "count_edges_with_prefix", None)
        if counter is not None:
            return counter(self._prefix)
        return sum(1 for e in self.inner.all_edges() if self._mine(e.id))

    def drop_database(self) -> Tuple[int, int]:
        return self.inner.delete_by_prefix(self._prefix)

    # -- optional bulk APIs ----------------------------------------------
    #
    # These exist on the concrete engines and would otherwise fall
    # through EngineDecorator.__getattr__ UNQUALIFIED — a label count
    # that sees every database, a clear() that wipes them all. Each is
    # re-scoped to this namespace here.

    def count_nodes_by_label(self, label: str) -> int:
        # the inner count spans all namespaces; count through the
        # prefix-filtered id listing instead
        return len(self.node_ids_by_label(label))

    def count_nodes_with_prefix(self, prefix: str) -> int:
        return self.inner.count_nodes_with_prefix(self._prefix + prefix)

    def count_edges_with_prefix(self, prefix: str) -> int:
        return self.inner.count_edges_with_prefix(self._prefix + prefix)

    def delete_by_prefix(self, prefix: str) -> Tuple[int, int]:
        return self.inner.delete_by_prefix(self._prefix + prefix)

    def clear(self) -> None:
        # clear THIS database, not the shared store under it
        self.inner.delete_by_prefix(self._prefix)
