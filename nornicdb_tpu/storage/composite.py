"""CompositeEngine: route reads across multiple engines.

Reference: pkg/storage composite_engine.go:48 (NewCompositeEngine) +
composite_routing.go — one logical view over several engines (multi-DB
composite reads). Writes go to the designated primary; reads fan out
and merge. ID-based lookups probe the primary first, then secondaries.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Sequence, Tuple

from nornicdb_tpu.errors import NotFoundError
from nornicdb_tpu.storage.types import Direction, Edge, Engine, Node


class CompositeEngine(Engine):
    def __init__(self, primary: Engine, secondaries: Sequence[Engine] = ()):
        self.primary = primary
        self.secondaries = list(secondaries)

    @property
    def engines(self) -> List[Engine]:
        return [self.primary, *self.secondaries]

    # -- writes: primary only --------------------------------------------

    def create_node(self, node: Node) -> None:
        self.primary.create_node(node)

    def update_node(self, node: Node) -> None:
        self.primary.update_node(node)

    def delete_node(self, node_id: str) -> None:
        self.primary.delete_node(node_id)

    def create_edge(self, edge: Edge) -> None:
        self.primary.create_edge(edge)

    def update_edge(self, edge: Edge) -> None:
        self.primary.update_edge(edge)

    def delete_edge(self, edge_id: str) -> None:
        self.primary.delete_edge(edge_id)

    def delete_by_prefix(self, prefix: str) -> Tuple[int, int]:
        return self.primary.delete_by_prefix(prefix)

    # -- reads: fan out, primary wins ties -------------------------------

    def _first(self, fn_name: str, *args):
        last_exc: Optional[Exception] = None
        for eng in self.engines:
            try:
                return getattr(eng, fn_name)(*args)
            except (KeyError, NotFoundError) as e:
                last_exc = e
        raise last_exc if last_exc is not None else KeyError(args)

    def get_node(self, node_id: str) -> Node:
        return self._first("get_node", node_id)

    def get_edge(self, edge_id: str) -> Edge:
        return self._first("get_edge", edge_id)

    def has_node(self, node_id: str) -> bool:
        return any(e.has_node(node_id) for e in self.engines)

    def has_edge(self, edge_id: str) -> bool:
        return any(e.has_edge(edge_id) for e in self.engines)

    def _merged_nodes(self, lists: Iterable[List[Node]]) -> List[Node]:
        seen = {}
        for lst in lists:  # primary first: its version wins duplicates
            for n in lst:
                if n.id not in seen:
                    seen[n.id] = n
        return list(seen.values())

    def get_nodes_by_label(self, label: str) -> List[Node]:
        return self._merged_nodes(
            e.get_nodes_by_label(label) for e in self.engines)

    def all_nodes(self) -> Iterable[Node]:
        return self._merged_nodes(
            list(e.all_nodes()) for e in self.engines)

    def batch_get_nodes(self, node_ids: Sequence[str]) -> List[Optional[Node]]:
        out: List[Optional[Node]] = [None] * len(node_ids)
        remaining = dict(enumerate(node_ids))
        for eng in self.engines:
            if not remaining:
                break
            got = eng.batch_get_nodes(list(remaining.values()))
            for (pos, _), node in zip(list(remaining.items()), got):
                if node is not None:
                    out[pos] = node
                    del remaining[pos]
        return out

    def get_edges_by_type(self, edge_type: str) -> List[Edge]:
        seen = {}
        for eng in self.engines:
            for e in eng.get_edges_by_type(edge_type):
                seen.setdefault(e.id, e)
        return list(seen.values())

    def all_edges(self) -> Iterable[Edge]:
        seen = {}
        for eng in self.engines:
            for e in eng.all_edges():
                seen.setdefault(e.id, e)
        return list(seen.values())

    def get_node_edges(
        self, node_id: str, direction: str = Direction.BOTH
    ) -> List[Edge]:
        seen = {}
        for eng in self.engines:
            try:
                for e in eng.get_node_edges(node_id, direction):
                    seen.setdefault(e.id, e)
            except (KeyError, NotFoundError):
                continue
        return list(seen.values())

    def degree(self, node_id: str, direction: str = Direction.BOTH) -> int:
        return len(self.get_node_edges(node_id, direction))

    def neighbors(
        self, node_id: str, direction: str = Direction.BOTH
    ) -> List[Node]:
        out = {}
        for e in self.get_node_edges(node_id, direction):
            other = e.end_node if e.start_node == node_id else e.start_node
            if other not in out:
                try:
                    out[other] = self.get_node(other)
                except (KeyError, NotFoundError):
                    pass
        return list(out.values())

    def count_nodes(self) -> int:
        return len(self._merged_nodes(
            list(e.all_nodes()) for e in self.engines))

    def count_edges(self) -> int:
        seen = set()
        for eng in self.engines:
            for e in eng.all_edges():
                seen.add(e.id)
        return len(seen)

    def list_namespaces(self) -> List[str]:
        out = set()
        for eng in self.engines:
            try:
                out.update(eng.list_namespaces())
            except Exception:
                continue
        return sorted(out)

    def flush(self) -> None:
        for eng in self.engines:
            eng.flush()

    def close(self) -> None:
        for eng in self.engines:
            eng.close()
