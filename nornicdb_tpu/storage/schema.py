"""Schema constraints: unique / property-type / relationship-endpoint /
temporal-interval validation, with persistence.

Reference: pkg/storage constraint_validation.go, property_validation.go,
temporal_constraint.go:9 (temporalInterval), schema.go,
schema_persistence.go. Constraints are checked by a decorator engine so
any base engine (memory, native disk, namespaced) gets the same
enforcement.
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from nornicdb_tpu.errors import ConstraintViolationError
from nornicdb_tpu.storage.types import Edge, Engine, EngineDecorator, Node


class ConstraintViolation(ConstraintViolationError, ValueError):
    """A mutation violated a schema constraint."""


PROPERTY_TYPES = {
    "string": str,
    "int": int,
    "float": (int, float),
    "bool": bool,
    "list": (list, tuple),
    "map": dict,
}


@dataclass
class Constraint:
    """One constraint definition.

    kinds:
      ``unique``        — (label, property) values unique across nodes
      ``exists``        — (label, property) must be present & non-null
      ``type``          — (label, property) must match ``property_type``
      ``rel_endpoints`` — edges of ``rel_type`` must connect
                          ``start_label`` -> ``end_label``
      ``temporal``      — (label, property) pair names an interval:
                          ``property`` (start) <= ``property2`` (end)
    """

    name: str
    kind: str
    label: str = ""
    property: str = ""
    property2: str = ""
    property_type: str = ""
    rel_type: str = ""
    start_label: str = ""
    end_label: str = ""

    def to_dict(self) -> Dict[str, Any]:
        return asdict(self)

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "Constraint":
        return Constraint(**{k: d.get(k, "") for k in (
            "name", "kind", "label", "property", "property2",
            "property_type", "rel_type", "start_label", "end_label")})


class SchemaManager:
    """Holds constraint definitions + optional JSON persistence
    (reference: schema_persistence.go)."""

    def __init__(self, path: Optional[str] = None):
        self._path = path
        self._constraints: Dict[str, Constraint] = {}
        self._lock = threading.Lock()
        if path and os.path.exists(path):
            with open(path, "r", encoding="utf-8") as f:
                for d in json.load(f):
                    c = Constraint.from_dict(d)
                    self._constraints[c.name] = c

    def _persist(self) -> None:
        if not self._path:
            return
        tmp = self._path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump([c.to_dict() for c in self._constraints.values()], f)
        os.replace(tmp, self._path)

    def add(self, c: Constraint) -> None:
        with self._lock:
            if c.name in self._constraints:
                raise ConstraintViolation(f"constraint exists: {c.name}")
            if c.kind not in ("unique", "exists", "type", "rel_endpoints", "temporal"):
                raise ConstraintViolation(f"unknown constraint kind: {c.kind}")
            self._constraints[c.name] = c
            self._persist()

    def drop(self, name: str) -> bool:
        with self._lock:
            existed = self._constraints.pop(name, None) is not None
            if existed:
                self._persist()
            return existed

    def list(self) -> List[Constraint]:
        with self._lock:
            return list(self._constraints.values())

    def for_label(self, label: str) -> List[Constraint]:
        with self._lock:
            return [c for c in self._constraints.values()
                    if c.label == label or not c.label]

    def applicable(self, labels: List[str]) -> List[Constraint]:
        """Constraints that apply to a node with these labels — global
        (label="") constraints apply to every node, even label-less ones."""
        lset = set(labels)
        with self._lock:
            return [c for c in self._constraints.values()
                    if c.kind != "rel_endpoints"
                    and (not c.label or c.label in lset)]

    def for_rel_type(self, rel_type: str) -> List[Constraint]:
        with self._lock:
            return [c for c in self._constraints.values()
                    if c.kind == "rel_endpoints" and c.rel_type == rel_type]


def _check_node(storage: Engine, sm: SchemaManager, node: Node,
                exclude_id: Optional[str] = None,
                unique_index: Optional["UniqueIndex"] = None) -> None:
    for c in sm.applicable(node.labels):
        label = c.label or "(any)"
        if c.kind == "exists":
            if node.properties.get(c.property) is None:
                raise ConstraintViolation(
                    f"{c.name}: {label}.{c.property} must exist")
        elif c.kind == "type":
            v = node.properties.get(c.property)
            want = PROPERTY_TYPES.get(c.property_type)
            if v is not None and want is not None and not isinstance(v, want):
                raise ConstraintViolation(
                    f"{c.name}: {label}.{c.property} must be {c.property_type}")
            if (v is not None and c.property_type == "int"
                    and isinstance(v, bool)):
                # bool is an int subclass; an int constraint must still
                # reject True/False
                raise ConstraintViolation(
                    f"{c.name}: {label}.{c.property} must be int")
        elif c.kind == "unique":
            v = node.properties.get(c.property)
            if v is None:
                continue
            owner = unique_index.lookup(c, v) if unique_index is not None else None
            if unique_index is None:
                # no index available: fall back to a scan
                others = (storage.get_nodes_by_label(c.label) if c.label
                          else storage.all_nodes())
                for other in others:
                    if other.id != (exclude_id or node.id) \
                            and other.properties.get(c.property) == v:
                        owner = other.id
                        break
            if owner is not None and owner != (exclude_id or node.id):
                raise ConstraintViolation(
                    f"{c.name}: duplicate {label}.{c.property}={v!r}")
        elif c.kind == "temporal":
            start = node.properties.get(c.property)
            end = node.properties.get(c.property2)
            if start is not None and end is not None:
                try:
                    if start > end:
                        raise ConstraintViolation(
                            f"{c.name}: interval {c.property} > {c.property2}")
                except TypeError:
                    raise ConstraintViolation(
                        f"{c.name}: interval endpoints not comparable")


def _check_edge(storage: Engine, sm: SchemaManager, edge: Edge) -> None:
    for c in sm.for_rel_type(edge.type):
        try:
            start = storage.get_node(edge.start_node)
            end = storage.get_node(edge.end_node)
        except KeyError:
            return  # endpoint existence is the engine's own check
        if c.start_label and c.start_label not in start.labels:
            raise ConstraintViolation(
                f"{c.name}: {edge.type} start must be :{c.start_label}")
        if c.end_label and c.end_label not in end.labels:
            raise ConstraintViolation(
                f"{c.name}: {edge.type} end must be :{c.end_label}")


class UniqueIndex:
    """Maintained (constraint, value) -> node_id map so unique checks are
    O(1) instead of a per-insert label scan (the reference backs unique
    constraints with an index). Built lazily per constraint, kept fresh by
    ConstrainedEngine's mutation hooks."""

    def __init__(self, storage: Engine):
        self._storage = storage
        # forward: key -> {value: node_id}; reverse: key -> {node_id: value}
        # — the reverse map makes per-mutation eviction O(1) instead of a
        # full value-map scan
        self._maps: Dict[Tuple[str, str], Dict[Any, str]] = {}
        self._owners: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self._lock = threading.Lock()

    def _key(self, c: Constraint) -> Tuple[str, str]:
        return (c.label, c.property)

    @staticmethod
    def _hashable(v: Any) -> Any:
        try:
            hash(v)
            return v
        except TypeError:
            return repr(v)

    def _ensure(self, c: Constraint) -> Dict[Any, str]:
        key = self._key(c)
        m = self._maps.get(key)
        if m is None:
            m = {}
            owners: Dict[str, Any] = {}
            nodes = (self._storage.get_nodes_by_label(c.label) if c.label
                     else list(self._storage.all_nodes()))
            for n in nodes:
                v = n.properties.get(c.property)
                if v is not None:
                    hv = self._hashable(v)
                    m[hv] = n.id
                    owners[n.id] = hv
            self._maps[key] = m
            self._owners[key] = owners
        return m

    def lookup(self, c: Constraint, value: Any) -> Optional[str]:
        with self._lock:
            return self._ensure(c).get(self._hashable(value))

    def on_upsert(self, constraints: List[Constraint], node: Node) -> None:
        with self._lock:
            for c in constraints:
                if c.kind != "unique":
                    continue
                if c.label and c.label not in node.labels:
                    continue
                key = self._key(c)
                m = self._maps.get(key)
                if m is None:
                    continue  # not built yet; next lookup scans fresh
                owners = self._owners[key]
                old = owners.pop(node.id, None)
                if old is not None and m.get(old) == node.id:
                    del m[old]
                v = node.properties.get(c.property)
                if v is not None:
                    hv = self._hashable(v)
                    m[hv] = node.id
                    owners[node.id] = hv

    def on_delete(self, node_id: str) -> None:
        with self._lock:
            for key, owners in self._owners.items():
                old = owners.pop(node_id, None)
                if old is not None:
                    m = self._maps[key]
                    if m.get(old) == node_id:
                        del m[old]


class ConstrainedEngine(EngineDecorator):
    """Decorator enforcing SchemaManager constraints on every mutation."""

    def __init__(self, inner: Engine, schema: Optional[SchemaManager] = None):
        super().__init__(inner)
        self.schema = schema or SchemaManager()
        self._unique = UniqueIndex(inner)

    def create_node(self, node: Node) -> None:
        _check_node(self.inner, self.schema, node, unique_index=self._unique)
        self.inner.create_node(node)
        self._unique.on_upsert(self.schema.list(), node)

    def update_node(self, node: Node) -> None:
        _check_node(self.inner, self.schema, node, exclude_id=node.id,
                    unique_index=self._unique)
        self.inner.update_node(node)
        self._unique.on_upsert(self.schema.list(), node)

    def delete_node(self, node_id: str) -> None:
        self.inner.delete_node(node_id)
        self._unique.on_delete(node_id)

    def create_edge(self, edge: Edge) -> None:
        _check_edge(self.inner, self.schema, edge)
        self.inner.create_edge(edge)

    def validate_existing(self) -> List[str]:
        """Sweep the store, returning violations (used when adding a
        constraint over existing data)."""
        problems: List[str] = []
        for node in self.inner.all_nodes():
            try:
                _check_node(self.inner, self.schema, node, exclude_id=node.id)
            except ConstraintViolation as e:
                problems.append(str(e))
        for edge in self.inner.all_edges():
            try:
                _check_edge(self.inner, self.schema, edge)
            except ConstraintViolation as e:
                problems.append(str(e))
        return problems


# ---------------------------------------------------------------------------
# Receipts (reference: pkg/storage/receipt.go:13,24 — mutation receipts
# tied to WAL sequence, hash-chained for an audit ledger)
# ---------------------------------------------------------------------------

import hashlib


@dataclass
class Receipt:
    sequence: int
    operation: str
    entity_id: str
    timestamp_ms: int
    prev_hash: str
    hash: str = ""

    def compute_hash(self) -> str:
        payload = f"{self.sequence}|{self.operation}|{self.entity_id}|{self.timestamp_ms}|{self.prev_hash}"
        return hashlib.sha256(payload.encode()).hexdigest()


class ReceiptLedger:
    """Hash-chained mutation receipts; verifiable like a mini audit chain."""

    def __init__(self) -> None:
        self._receipts: List[Receipt] = []
        self._lock = threading.Lock()

    def record(self, operation: str, entity_id: str, sequence: Optional[int] = None,
               timestamp_ms: Optional[int] = None) -> Receipt:
        from nornicdb_tpu.storage.types import now_ms

        with self._lock:
            prev = self._receipts[-1].hash if self._receipts else "genesis"
            r = Receipt(
                sequence=sequence if sequence is not None else len(self._receipts) + 1,
                operation=operation,
                entity_id=entity_id,
                timestamp_ms=timestamp_ms if timestamp_ms is not None else now_ms(),
                prev_hash=prev,
            )
            r.hash = r.compute_hash()
            self._receipts.append(r)
            return r

    def verify(self) -> Tuple[bool, int]:
        """Returns (ok, first_bad_index). Tamper with any receipt and the
        chain breaks from there."""
        with self._lock:
            prev = "genesis"
            for i, r in enumerate(self._receipts):
                if r.prev_hash != prev or r.hash != r.compute_hash():
                    return False, i
                prev = r.hash
            return True, -1

    def all(self) -> List[Receipt]:
        with self._lock:
            return list(self._receipts)
