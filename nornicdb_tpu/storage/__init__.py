"""Storage layer: composable engine decorators.

Production chain (reference: pkg/nornicdb/db.go:742-947):
``DurableEngine (Memory+WAL) -> [AsyncEngine] -> NamespacedEngine``.
"""

from nornicdb_tpu.storage.types import (  # noqa: F401
    Direction,
    Edge,
    EdgeID,
    Engine,
    EngineDecorator,
    ListenableEngine,
    MutationListener,
    Node,
    NodeID,
    now_ms,
)
from nornicdb_tpu.storage.memory import MemoryEngine  # noqa: F401
from nornicdb_tpu.storage.wal import WAL, ReplayResult  # noqa: F401
from nornicdb_tpu.storage.wal_engine import DurableEngine, WALEngine  # noqa: F401
from nornicdb_tpu.storage.async_engine import AsyncEngine, FlushResult  # noqa: F401
from nornicdb_tpu.storage.namespaced import DEFAULT_DB, NamespacedEngine  # noqa: F401
