"""Storage layer: composable engine decorators.

Production chain (reference: pkg/nornicdb/db.go:742-947):
``DurableEngine (Memory+WAL) -> [AsyncEngine] -> NamespacedEngine``.
"""

from nornicdb_tpu.storage.types import (  # noqa: F401
    Direction,
    Edge,
    EdgeID,
    Engine,
    EngineDecorator,
    ListenableEngine,
    MutationListener,
    Node,
    NodeID,
    now_ms,
)
from nornicdb_tpu.storage.composite import CompositeEngine  # noqa: F401
from nornicdb_tpu.storage.memory import MemoryEngine  # noqa: F401
from nornicdb_tpu.storage.wal import WAL, ReplayResult  # noqa: F401
from nornicdb_tpu.storage.wal_engine import DurableEngine, WALEngine  # noqa: F401
from nornicdb_tpu.storage.async_engine import AsyncEngine, FlushResult  # noqa: F401
from nornicdb_tpu.storage.namespaced import DEFAULT_DB, NamespacedEngine  # noqa: F401
from nornicdb_tpu.storage.schema import (  # noqa: F401
    ConstrainedEngine,
    Constraint,
    ConstraintViolation,
    Receipt,
    ReceiptLedger,
    SchemaManager,
)
from nornicdb_tpu.storage.partition_store import PartitionStore  # noqa: F401


def make_persistent_engine(data_dir: str, sync_every_write: bool = False,
                           encryptor=None):
    """Best persistent base engine available, honoring whatever format is
    already on disk: a dir with WAL/snapshot files reopens as the
    pure-Python DurableEngine, a dir with a native kv/ store reopens as
    the C++ DiskEngine. Fresh dirs prefer native when the toolchain can
    build it. Open failures of an EXISTING store propagate — corruption
    must not silently masquerade as an empty database."""
    import glob
    import os

    has_python_format = bool(
        glob.glob(os.path.join(data_dir, "wal-*.log"))
        or glob.glob(os.path.join(data_dir, "snapshot-*.bin"))
    )
    has_native_format = os.path.isdir(os.path.join(data_dir, "kv"))
    if has_python_format and has_native_format:
        raise RuntimeError(
            f"{data_dir} holds BOTH pure-Python (wal-*/snapshot-*) and "
            "native (kv/) stores; refusing to guess — open explicitly with "
            "engine='python' or engine='native'"
        )
    if has_python_format:
        return DurableEngine(data_dir, sync_every_write=sync_every_write,
                             encryptor=encryptor)
    if has_native_format:
        from nornicdb_tpu.storage.disk import DiskEngine

        return DiskEngine(data_dir, sync_every_write=sync_every_write,
                          encryptor=encryptor)
    # fresh directory: pick native if buildable, else pure Python
    try:
        from nornicdb_tpu.storage.disk import DiskEngine, native_available
    except ImportError:
        return DurableEngine(data_dir, sync_every_write=sync_every_write,
                             encryptor=encryptor)
    if native_available():
        return DiskEngine(data_dir, sync_every_write=sync_every_write,
                          encryptor=encryptor)
    return DurableEngine(data_dir, sync_every_write=sync_every_write,
                         encryptor=encryptor)
