"""Core storage types and the Engine contract.

Re-expresses the reference's storage contract (pkg/storage/types.go:363-422:
``Engine`` interface — node/edge CRUD, label/type-indexed lookups, degree
queries, bulk ops, BatchGetNodes, counts, DeleteByPrefix) as an idiomatic
Python ABC. All engines must be thread-safe.
"""

from __future__ import annotations

import threading
import time
from abc import ABC, abstractmethod
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

NodeID = str
EdgeID = str


def now_ms() -> int:
    return int(time.time() * 1000)


@dataclass
class Node:
    """A graph node (reference: pkg/storage/types.go ``Node``).

    ``embedding`` is the whole-document vector; ``chunk_embeddings`` holds
    per-chunk vectors for long documents (reference: pkg/nornicdb/db.go:224
    ``ChunkEmbeddings``).
    """

    id: NodeID
    labels: List[str] = field(default_factory=list)
    properties: Dict[str, Any] = field(default_factory=dict)
    created_at: int = 0
    updated_at: int = 0
    embedding: Optional[List[float]] = None
    chunk_embeddings: Optional[List[List[float]]] = None

    def copy(self) -> "Node":
        return Node(
            id=self.id,
            labels=list(self.labels),
            properties=dict(self.properties),
            created_at=self.created_at,
            updated_at=self.updated_at,
            embedding=list(self.embedding) if self.embedding is not None else None,
            chunk_embeddings=[list(c) for c in self.chunk_embeddings]
            if self.chunk_embeddings is not None
            else None,
        )

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "id": self.id,
            "labels": self.labels,
            "properties": self.properties,
            "created_at": self.created_at,
            "updated_at": self.updated_at,
        }
        if self.embedding is not None:
            d["embedding"] = self.embedding
        if self.chunk_embeddings is not None:
            d["chunk_embeddings"] = self.chunk_embeddings
        return d

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "Node":
        return Node(
            id=d["id"],
            labels=list(d.get("labels") or []),
            properties=dict(d.get("properties") or {}),
            created_at=int(d.get("created_at") or 0),
            updated_at=int(d.get("updated_at") or 0),
            embedding=d.get("embedding"),
            chunk_embeddings=d.get("chunk_embeddings"),
        )


@dataclass
class Edge:
    """A directed, typed relationship (reference: pkg/storage/types.go ``Edge``)."""

    id: EdgeID
    type: str
    start_node: NodeID
    end_node: NodeID
    properties: Dict[str, Any] = field(default_factory=dict)
    created_at: int = 0
    updated_at: int = 0

    def copy(self) -> "Edge":
        return Edge(
            id=self.id,
            type=self.type,
            start_node=self.start_node,
            end_node=self.end_node,
            properties=dict(self.properties),
            created_at=self.created_at,
            updated_at=self.updated_at,
        )

    def to_dict(self) -> Dict[str, Any]:
        return {
            "id": self.id,
            "type": self.type,
            "start_node": self.start_node,
            "end_node": self.end_node,
            "properties": self.properties,
            "created_at": self.created_at,
            "updated_at": self.updated_at,
        }

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "Edge":
        return Edge(
            id=d["id"],
            type=d["type"],
            start_node=d["start_node"],
            end_node=d["end_node"],
            properties=dict(d.get("properties") or {}),
            created_at=int(d.get("created_at") or 0),
            updated_at=int(d.get("updated_at") or 0),
        )


class Direction:
    OUTGOING = "out"
    INCOMING = "in"
    BOTH = "both"


class Engine(ABC):
    """Storage engine contract (reference: pkg/storage/types.go:363-422).

    Engines compose as decorators; the production chain is
    ``DiskEngine -> WALEngine -> [AsyncEngine] -> NamespacedEngine``
    (reference: pkg/nornicdb/db.go:742-947).
    """

    # -- nodes ----------------------------------------------------------

    @abstractmethod
    def create_node(self, node: Node) -> None: ...

    @abstractmethod
    def get_node(self, node_id: NodeID) -> Node: ...

    @abstractmethod
    def update_node(self, node: Node) -> None: ...

    @abstractmethod
    def delete_node(self, node_id: NodeID) -> None:
        """Delete a node and all its edges."""

    @abstractmethod
    def get_nodes_by_label(self, label: str) -> List[Node]: ...

    def node_ids_by_label(self, label: str) -> List[NodeID]:
        """IDs only — lets paged readers (GraphQL nodes(label:), UI
        listings) sort/slice on ids and fetch just one page instead of
        copying every labeled node. Engines with a label index override
        with a key-only path."""
        return [n.id for n in self.get_nodes_by_label(label)]

    @abstractmethod
    def all_nodes(self) -> Iterable[Node]: ...

    def batch_get_nodes(self, node_ids: Sequence[NodeID]) -> List[Optional[Node]]:
        """Batched fetch; missing nodes yield None (reference BatchGetNodes)."""
        out: List[Optional[Node]] = []
        for nid in node_ids:
            try:
                out.append(self.get_node(nid))
            except KeyError:
                out.append(None)
        return out

    def has_node(self, node_id: NodeID) -> bool:
        try:
            self.get_node(node_id)
            return True
        except KeyError:
            return False

    def has_edge(self, edge_id: EdgeID) -> bool:
        try:
            self.get_edge(edge_id)
            return True
        except KeyError:
            return False

    # -- edges ----------------------------------------------------------

    @abstractmethod
    def create_edge(self, edge: Edge) -> None: ...

    @abstractmethod
    def get_edge(self, edge_id: EdgeID) -> Edge: ...

    @abstractmethod
    def update_edge(self, edge: Edge) -> None: ...

    @abstractmethod
    def delete_edge(self, edge_id: EdgeID) -> None: ...

    @abstractmethod
    def get_edges_by_type(self, edge_type: str) -> List[Edge]: ...

    @abstractmethod
    def all_edges(self) -> Iterable[Edge]: ...

    @abstractmethod
    def get_node_edges(
        self, node_id: NodeID, direction: str = Direction.BOTH
    ) -> List[Edge]: ...

    def degree(self, node_id: NodeID, direction: str = Direction.BOTH) -> int:
        return len(self.get_node_edges(node_id, direction))

    def neighbors(
        self, node_id: NodeID, direction: str = Direction.BOTH
    ) -> List[NodeID]:
        out: List[NodeID] = []
        for e in self.get_node_edges(node_id, direction):
            if e.start_node == node_id and direction in (
                Direction.OUTGOING,
                Direction.BOTH,
            ):
                out.append(e.end_node)
            if e.end_node == node_id and direction in (
                Direction.INCOMING,
                Direction.BOTH,
            ):
                out.append(e.start_node)
        return out

    # -- counts / maintenance -------------------------------------------

    @abstractmethod
    def count_nodes(self) -> int: ...

    @abstractmethod
    def count_edges(self) -> int: ...

    def delete_by_prefix(self, prefix: str) -> Tuple[int, int]:
        """Delete all nodes/edges whose IDs start with prefix; multi-DB drop
        (reference: types.go DeleteByPrefix). Returns (nodes, edges) deleted."""
        nodes = [n.id for n in self.all_nodes() if n.id.startswith(prefix)]
        edges = [
            e.id
            for e in self.all_edges()
            if e.id.startswith(prefix)
            or e.start_node.startswith(prefix)
            or e.end_node.startswith(prefix)
        ]
        for eid in edges:
            try:
                self.delete_edge(eid)
            except KeyError:
                pass
        for nid in nodes:
            try:
                self.delete_node(nid)
            except KeyError:
                pass
        return len(nodes), len(edges)

    def list_namespaces(self) -> List[str]:
        """Distinct ``db:`` prefixes present (reference: NamespaceLister,
        types.go:442)."""
        seen = set()
        for n in self.all_nodes():
            if ":" in n.id:
                seen.add(n.id.split(":", 1)[0])
        return sorted(seen)

    def flush(self) -> None:
        """Flush any buffered writes (no-op for synchronous engines)."""

    def close(self) -> None:  # noqa: B027
        """Release resources."""


class EngineDecorator(Engine):
    """Base for decorator engines: forwards everything to ``inner``.

    Optional extension methods (count_nodes_with_prefix, …) are forwarded
    via __getattr__ so a decorator chain stays transparent to getattr
    probes (reference: optional extension interfaces like
    PrefixStatsEngine, types.go:432)."""

    def __init__(self, inner: Engine):
        self.inner = inner

    def __getattr__(self, name: str):
        if name == "inner":  # not yet set during __init__
            raise AttributeError(name)
        return getattr(self.inner, name)

    def create_node(self, node: Node) -> None:
        self.inner.create_node(node)

    def get_node(self, node_id: NodeID) -> Node:
        return self.inner.get_node(node_id)

    def update_node(self, node: Node) -> None:
        self.inner.update_node(node)

    def delete_node(self, node_id: NodeID) -> None:
        self.inner.delete_node(node_id)

    def get_nodes_by_label(self, label: str) -> List[Node]:
        return self.inner.get_nodes_by_label(label)

    def node_ids_by_label(self, label: str) -> List[NodeID]:
        return self.inner.node_ids_by_label(label)

    def all_nodes(self) -> Iterable[Node]:
        return self.inner.all_nodes()

    def batch_get_nodes(self, node_ids: Sequence[NodeID]) -> List[Optional[Node]]:
        return self.inner.batch_get_nodes(node_ids)

    def create_edge(self, edge: Edge) -> None:
        self.inner.create_edge(edge)

    def get_edge(self, edge_id: EdgeID) -> Edge:
        return self.inner.get_edge(edge_id)

    def update_edge(self, edge: Edge) -> None:
        self.inner.update_edge(edge)

    def delete_edge(self, edge_id: EdgeID) -> None:
        self.inner.delete_edge(edge_id)

    def get_edges_by_type(self, edge_type: str) -> List[Edge]:
        return self.inner.get_edges_by_type(edge_type)

    def all_edges(self) -> Iterable[Edge]:
        return self.inner.all_edges()

    def get_node_edges(
        self, node_id: NodeID, direction: str = Direction.BOTH
    ) -> List[Edge]:
        return self.inner.get_node_edges(node_id, direction)

    def degree(self, node_id: NodeID, direction: str = Direction.BOTH) -> int:
        return self.inner.degree(node_id, direction)

    def has_node(self, node_id: NodeID) -> bool:
        return self.inner.has_node(node_id)

    def has_edge(self, edge_id: EdgeID) -> bool:
        return self.inner.has_edge(edge_id)

    def count_nodes(self) -> int:
        return self.inner.count_nodes()

    def count_edges(self) -> int:
        return self.inner.count_edges()

    def delete_by_prefix(self, prefix: str) -> Tuple[int, int]:
        return self.inner.delete_by_prefix(prefix)

    def list_namespaces(self) -> List[str]:
        return self.inner.list_namespaces()

    def flush(self) -> None:
        self.inner.flush()

    def close(self) -> None:
        self.inner.close()


class MutationListener:
    """Callback hooks fired after successful mutations; used to drive the
    embed queue and search-index invalidation (reference: node-mutation
    callbacks wired at pkg/nornicdb/db.go:1076-1080)."""

    def on_node_upsert(self, node: Node) -> None: ...

    def on_node_delete(self, node_id: NodeID) -> None: ...

    def on_edge_upsert(self, edge: Edge) -> None: ...

    def on_edge_delete(self, edge_id: EdgeID) -> None: ...

    def on_bulk_change(self) -> None:
        """Coarse invalidation hook for bulk mutations that carry no
        per-entity events (clear, delete_by_prefix)."""


class ListenableEngine(EngineDecorator):
    """Decorator that fans out mutation events to registered listeners."""

    def __init__(self, inner: Engine):
        super().__init__(inner)
        self._listeners: List[MutationListener] = []
        self._lock = threading.Lock()

    def add_listener(self, listener: MutationListener) -> None:
        with self._lock:
            self._listeners.append(listener)

    def _each(self):
        with self._lock:
            return list(self._listeners)

    def create_node(self, node: Node) -> None:
        self.inner.create_node(node)
        for l in self._each():
            l.on_node_upsert(node)

    def update_node(self, node: Node) -> None:
        self.inner.update_node(node)
        for l in self._each():
            l.on_node_upsert(node)

    def delete_node(self, node_id: NodeID) -> None:
        self.inner.delete_node(node_id)
        for l in self._each():
            l.on_node_delete(node_id)

    def create_edge(self, edge: Edge) -> None:
        self.inner.create_edge(edge)
        for l in self._each():
            l.on_edge_upsert(edge)

    def update_edge(self, edge: Edge) -> None:
        self.inner.update_edge(edge)
        for l in self._each():
            l.on_edge_upsert(edge)

    def delete_edge(self, edge_id: EdgeID) -> None:
        self.inner.delete_edge(edge_id)
        for l in self._each():
            l.on_edge_delete(edge_id)

    # bulk mutations would otherwise fall through __getattr__ with NO
    # events — a generation-keyed cache above this engine would then
    # serve state from before a clear()/prefix-drop forever

    def delete_by_prefix(self, prefix: str):
        out = self.inner.delete_by_prefix(prefix)
        for l in self._each():
            l.on_bulk_change()
        return out

    def clear(self) -> None:
        self.inner.clear()
        for l in self._each():
            l.on_bulk_change()
