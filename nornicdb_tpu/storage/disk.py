"""Native persistent storage engine over the nornickv C++ KV store.

TPU-native equivalent of the reference's BadgerEngine (reference:
pkg/storage/badger.go:70; key-space layout mirrors badger_nodes.go /
badger_edges.go / badger_queries.go): node/edge records plus secondary
key spaces for label, edge-type, and adjacency lookups, all inside one
log-structured store (native/nornickv.cpp, loaded via ctypes — no
pybind11 in this image). Values are msgpack.

Key spaces:
  ``n:<id>``                     node record
  ``e:<id>``                     edge record
  ``l:<label>\\x00<id>``          label index (empty value)
  ``t:<type>\\x00<id>``           edge-type index
  ``a:<node>\\x00o\\x00<edge>``    outgoing adjacency
  ``a:<node>\\x00i\\x00<edge>``    incoming adjacency
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Iterable, List, Optional, Sequence, Tuple

import msgpack

from nornicdb_tpu.storage.types import Direction, Edge, EdgeID, Engine, Node, NodeID, now_ms

_SEP = b"\x00"
_ENC_MAGIC = b"NKE1"


def _load_lib() -> ctypes.CDLL:
    from nornicdb_tpu._native import load_build_module

    so = load_build_module("build.py").build()
    lib = ctypes.CDLL(so)
    lib.nkv_open.restype = ctypes.c_void_p
    lib.nkv_open.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_long]
    lib.nkv_put.restype = ctypes.c_int
    lib.nkv_put.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
                            ctypes.c_char_p, ctypes.c_int]
    lib.nkv_get.restype = ctypes.c_int
    lib.nkv_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
                            ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_int)]
    lib.nkv_has.restype = ctypes.c_int
    lib.nkv_has.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
    lib.nkv_delete.restype = ctypes.c_int
    lib.nkv_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
    lib.nkv_count.restype = ctypes.c_long
    lib.nkv_count.argtypes = [ctypes.c_void_p]
    lib.nkv_count_prefix.restype = ctypes.c_long
    lib.nkv_count_prefix.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
    lib.nkv_live_bytes.restype = ctypes.c_long
    lib.nkv_live_bytes.argtypes = [ctypes.c_void_p]
    lib.nkv_dead_bytes.restype = ctypes.c_long
    lib.nkv_dead_bytes.argtypes = [ctypes.c_void_p]
    lib.nkv_repaired.restype = ctypes.c_int
    lib.nkv_repaired.argtypes = [ctypes.c_void_p]
    lib.nkv_sync.restype = ctypes.c_int
    lib.nkv_sync.argtypes = [ctypes.c_void_p]
    lib.nkv_compact.restype = ctypes.c_int
    lib.nkv_compact.argtypes = [ctypes.c_void_p]
    lib.nkv_scan.restype = ctypes.c_void_p
    lib.nkv_scan.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
    lib.nkv_scan_next.restype = ctypes.c_int
    lib.nkv_scan_next.argtypes = [ctypes.c_void_p,
                                  ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_int),
                                  ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_int)]
    lib.nkv_scan_free.argtypes = [ctypes.c_void_p]
    lib.nkv_free.argtypes = [ctypes.c_void_p]
    lib.nkv_close.argtypes = [ctypes.c_void_p]
    return lib


_lib_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None


def get_lib() -> ctypes.CDLL:
    global _lib
    with _lib_lock:
        if _lib is None:
            _lib = _load_lib()
        return _lib


def native_available() -> bool:
    try:
        get_lib()
        return True
    except Exception:
        return False


class DiskKV:
    """Thin Python handle over one nornickv store directory."""

    def __init__(self, directory: str, sync_every_write: bool = False,
                 max_segment_bytes: int = 64 * 1024 * 1024):
        self._lib = get_lib()
        os.makedirs(directory, exist_ok=True)
        self._h = self._lib.nkv_open(directory.encode(), 1 if sync_every_write else 0,
                                     max_segment_bytes)
        if not self._h:
            raise IOError(f"nkv_open failed for {directory}")
        self._closed = False

    def put(self, key: bytes, value: bytes) -> None:
        if self._lib.nkv_put(self._h, key, len(key), value, len(value)) != 0:
            raise IOError("nkv_put failed")

    def get(self, key: bytes) -> Optional[bytes]:
        val = ctypes.c_void_p()
        vlen = ctypes.c_int()
        rc = self._lib.nkv_get(self._h, key, len(key), ctypes.byref(val), ctypes.byref(vlen))
        if rc == 1:
            return None
        if rc != 0:
            raise IOError("nkv_get failed")
        try:
            return ctypes.string_at(val, vlen.value)
        finally:
            self._lib.nkv_free(val)

    def has(self, key: bytes) -> bool:
        return self._lib.nkv_has(self._h, key, len(key)) == 1

    def delete(self, key: bytes) -> bool:
        rc = self._lib.nkv_delete(self._h, key, len(key))
        if rc < 0:
            raise IOError("nkv_delete failed")
        return rc == 0

    def count(self) -> int:
        return self._lib.nkv_count(self._h)

    def count_prefix(self, prefix: bytes) -> int:
        return self._lib.nkv_count_prefix(self._h, prefix, len(prefix))

    def scan(self, prefix: bytes) -> Iterable[Tuple[bytes, bytes]]:
        it = self._lib.nkv_scan(self._h, prefix, len(prefix))
        try:
            while True:
                k = ctypes.c_void_p()
                klen = ctypes.c_int()
                v = ctypes.c_void_p()
                vlen = ctypes.c_int()
                rc = self._lib.nkv_scan_next(it, ctypes.byref(k), ctypes.byref(klen),
                                             ctypes.byref(v), ctypes.byref(vlen))
                if rc == 1:
                    return
                if rc != 0:
                    raise IOError("nkv_scan_next failed")
                key = ctypes.string_at(k, klen.value)
                val = ctypes.string_at(v, vlen.value)
                self._lib.nkv_free(k)
                self._lib.nkv_free(v)
                yield key, val
        finally:
            self._lib.nkv_scan_free(it)

    def sync(self) -> None:
        self._lib.nkv_sync(self._h)

    def compact(self) -> None:
        if self._lib.nkv_compact(self._h) != 0:
            raise IOError("nkv_compact failed")

    @property
    def live_bytes(self) -> int:
        return self._lib.nkv_live_bytes(self._h)

    @property
    def dead_bytes(self) -> int:
        return self._lib.nkv_dead_bytes(self._h)

    @property
    def repaired(self) -> int:
        return self._lib.nkv_repaired(self._h)

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._lib.nkv_close(self._h)


class DiskEngine(Engine):
    """Engine over DiskKV with Badger-style secondary key spaces.

    Compacts automatically when dead bytes exceed both 64MB and half of
    live bytes (Badger value-log GC analog).
    """

    def __init__(self, data_dir: str, sync_every_write: bool = False,
                 auto_compact: bool = True, encryptor=None):
        import glob

        # refuse to create a native store beside pure-Python DurableEngine
        # data — that would shadow the existing database as empty
        if not os.path.isdir(os.path.join(data_dir, "kv")) and (
            glob.glob(os.path.join(data_dir, "wal-*.log"))
            or glob.glob(os.path.join(data_dir, "snapshot-*.bin"))
        ):
            raise ValueError(
                f"{data_dir} holds pure-Python engine data; open it with "
                "engine='python' (or migrate) instead of creating a native "
                "store beside it"
            )
        self.kv = DiskKV(os.path.join(data_dir, "kv"), sync_every_write=sync_every_write)
        self.auto_compact = auto_compact
        self._enc = encryptor
        self._verify_encryption_state()
        self._lock = threading.Lock()  # serializes multi-key mutations

    # -- helpers --------------------------------------------------------

    @staticmethod
    def _nk(node_id: str) -> bytes:
        return b"n:" + node_id.encode()

    @staticmethod
    def _ek(edge_id: str) -> bytes:
        return b"e:" + edge_id.encode()

    @staticmethod
    def _lk(label: str, node_id: str) -> bytes:
        return b"l:" + label.encode() + _SEP + node_id.encode()

    @staticmethod
    def _tk(edge_type: str, edge_id: str) -> bytes:
        return b"t:" + edge_type.encode() + _SEP + edge_id.encode()

    @staticmethod
    def _ak(node_id: str, direction: bytes, edge_id: str) -> bytes:
        return b"a:" + node_id.encode() + _SEP + direction + _SEP + edge_id.encode()

    _ENC_SENTINEL_KEY = b"\x00meta:enc"

    def _verify_encryption_state(self) -> None:
        """Fail at open on passphrase mismatch, BEFORE any write could mix
        records under different keys. A sentinel record is written on the
        first encrypted open; later opens must decrypt it."""
        from nornicdb_tpu.encryption import EncryptionError

        raw = self.kv.get(self._ENC_SENTINEL_KEY)
        if raw is not None:
            if raw[: len(_ENC_MAGIC)] == _ENC_MAGIC and self._enc is None:
                self.kv.close()
                raise EncryptionError(
                    "store is encrypted; open with the passphrase"
                )
            try:
                self._unpack(raw)  # raises EncryptionError on wrong key
            except EncryptionError:
                self.kv.close()
                raise
        elif self._enc is not None:
            if self.kv.count() > 0:
                self.kv.close()
                raise EncryptionError(
                    "store exists unencrypted; open without a passphrase "
                    "(or export/re-import to encrypt)"
                )
            self.kv.put(self._ENC_SENTINEL_KEY, self._pack({"enc": True}))

    def _pack(self, d) -> bytes:
        """Serialize a record, AES-256-GCM-wrapped when the store was
        opened with a passphrase (reference: at-rest encryption wired into
        the storage engine, db.go:776-805).

        Scope: record VALUES (node/edge documents) are encrypted; the KV
        index keys (ids, labels, edge types) stay plaintext because the
        engine's prefix scans depend on them. For full-record-at-rest
        (including identifiers) use engine="python", whose WAL+snapshot
        payloads are encrypted whole; for sensitive property values use
        field-level encryption (encryption.Encryptor.encrypt_field)."""
        from nornicdb_tpu.storage.wal import _typed_default

        raw = msgpack.packb(d, use_bin_type=True, default=_typed_default)
        if self._enc is not None:
            raw = _ENC_MAGIC + self._enc.encrypt(raw)
        return raw

    def _unpack(self, raw: bytes):
        if raw[: len(_ENC_MAGIC)] == _ENC_MAGIC:
            if self._enc is None:
                from nornicdb_tpu.encryption import EncryptionError

                raise EncryptionError(
                    "store is encrypted; open with the passphrase"
                )
            raw = self._enc.decrypt(raw[len(_ENC_MAGIC):])
        from nornicdb_tpu.storage.wal import _typed_hook

        return msgpack.unpackb(raw, raw=False, object_hook=_typed_hook)

    def _maybe_compact(self) -> None:
        if not self.auto_compact:
            return
        dead = self.kv.dead_bytes
        if dead > 64 * 1024 * 1024 and dead > self.kv.live_bytes // 2:
            self.kv.compact()

    # -- nodes ----------------------------------------------------------

    def create_node(self, node: Node) -> None:
        with self._lock:
            key = self._nk(node.id)
            if self.kv.has(key):
                raise ValueError(f"node exists: {node.id}")
            n = node.copy()
            ts = now_ms()
            n.created_at = n.created_at or ts
            n.updated_at = ts
            self.kv.put(key, self._pack(n.to_dict()))
            for label in n.labels:
                self.kv.put(self._lk(label, n.id), b"")

    def get_node(self, node_id: NodeID) -> Node:
        raw = self.kv.get(self._nk(node_id))
        if raw is None:
            raise KeyError(node_id)
        return Node.from_dict(self._unpack(raw))

    def update_node(self, node: Node) -> None:
        with self._lock:
            raw = self.kv.get(self._nk(node.id))
            if raw is None:
                raise KeyError(node.id)
            old = Node.from_dict(self._unpack(raw))
            n = node.copy()
            n.created_at = old.created_at
            n.updated_at = now_ms()
            for label in set(old.labels) - set(n.labels):
                self.kv.delete(self._lk(label, n.id))
            for label in set(n.labels) - set(old.labels):
                self.kv.put(self._lk(label, n.id), b"")
            self.kv.put(self._nk(n.id), self._pack(n.to_dict()))
        self._maybe_compact()

    def delete_node(self, node_id: NodeID) -> None:
        with self._lock:
            raw = self.kv.get(self._nk(node_id))
            if raw is None:
                raise KeyError(node_id)
            node = Node.from_dict(self._unpack(raw))
            for eid in [e.id for e in self._node_edges_locked(node_id, Direction.BOTH)]:
                self._delete_edge_locked(eid)
            for label in node.labels:
                self.kv.delete(self._lk(label, node_id))
            self.kv.delete(self._nk(node_id))
        self._maybe_compact()

    def get_nodes_by_label(self, label: str) -> List[Node]:
        return [n for n in self.batch_get_nodes(self.node_ids_by_label(label))
                if n is not None]

    def node_ids_by_label(self, label: str) -> List[NodeID]:
        prefix = b"l:" + label.encode() + _SEP
        return [k[len(prefix):].decode() for k, _ in self.kv.scan(prefix)]

    def all_nodes(self) -> Iterable[Node]:
        for _, raw in self.kv.scan(b"n:"):
            yield Node.from_dict(self._unpack(raw))

    def batch_get_nodes(self, node_ids: Sequence[NodeID]) -> List[Optional[Node]]:
        out: List[Optional[Node]] = []
        for nid in node_ids:
            raw = self.kv.get(self._nk(nid))
            out.append(None if raw is None else Node.from_dict(self._unpack(raw)))
        return out

    def has_node(self, node_id: NodeID) -> bool:
        return self.kv.has(self._nk(node_id))

    # -- edges ----------------------------------------------------------

    def create_edge(self, edge: Edge) -> None:
        with self._lock:
            key = self._ek(edge.id)
            if self.kv.has(key):
                raise ValueError(f"edge exists: {edge.id}")
            if not self.kv.has(self._nk(edge.start_node)):
                raise KeyError(edge.start_node)
            if not self.kv.has(self._nk(edge.end_node)):
                raise KeyError(edge.end_node)
            e = edge.copy()
            ts = now_ms()
            e.created_at = e.created_at or ts
            e.updated_at = ts
            self.kv.put(key, self._pack(e.to_dict()))
            self.kv.put(self._tk(e.type, e.id), b"")
            self.kv.put(self._ak(e.start_node, b"o", e.id), b"")
            self.kv.put(self._ak(e.end_node, b"i", e.id), b"")

    def get_edge(self, edge_id: EdgeID) -> Edge:
        raw = self.kv.get(self._ek(edge_id))
        if raw is None:
            raise KeyError(edge_id)
        return Edge.from_dict(self._unpack(raw))

    def update_edge(self, edge: Edge) -> None:
        with self._lock:
            raw = self.kv.get(self._ek(edge.id))
            if raw is None:
                raise KeyError(edge.id)
            old = Edge.from_dict(self._unpack(raw))
            e = edge.copy()
            e.created_at = old.created_at
            e.updated_at = now_ms()
            # endpoints/type are immutable in the reference; enforce the
            # same semantics as MemoryEngine so engine choice is invisible
            e.start_node, e.end_node, e.type = old.start_node, old.end_node, old.type
            self.kv.put(self._ek(e.id), self._pack(e.to_dict()))
        self._maybe_compact()

    def _delete_edge_locked(self, edge_id: EdgeID) -> None:
        raw = self.kv.get(self._ek(edge_id))
        if raw is None:
            raise KeyError(edge_id)
        edge = Edge.from_dict(self._unpack(raw))
        self.kv.delete(self._tk(edge.type, edge_id))
        self.kv.delete(self._ak(edge.start_node, b"o", edge_id))
        self.kv.delete(self._ak(edge.end_node, b"i", edge_id))
        self.kv.delete(self._ek(edge_id))

    def delete_edge(self, edge_id: EdgeID) -> None:
        with self._lock:
            self._delete_edge_locked(edge_id)
        self._maybe_compact()

    def get_edges_by_type(self, edge_type: str) -> List[Edge]:
        prefix = b"t:" + edge_type.encode() + _SEP
        out = []
        for k, _ in self.kv.scan(prefix):
            raw = self.kv.get(self._ek(k[len(prefix):].decode()))
            if raw is not None:
                out.append(Edge.from_dict(self._unpack(raw)))
        return out

    def all_edges(self) -> Iterable[Edge]:
        for _, raw in self.kv.scan(b"e:"):
            yield Edge.from_dict(self._unpack(raw))

    def _node_edges_locked(self, node_id: NodeID, direction: str) -> List[Edge]:
        dirs = []
        if direction in (Direction.OUTGOING, Direction.BOTH):
            dirs.append(b"o")
        if direction in (Direction.INCOMING, Direction.BOTH):
            dirs.append(b"i")
        out: List[Edge] = []
        seen = set()
        for d in dirs:
            prefix = b"a:" + node_id.encode() + _SEP + d + _SEP
            for k, _ in self.kv.scan(prefix):
                eid = k[len(prefix):].decode()
                if eid in seen:
                    continue
                seen.add(eid)
                raw = self.kv.get(self._ek(eid))
                if raw is not None:
                    out.append(Edge.from_dict(self._unpack(raw)))
        return out

    def get_node_edges(self, node_id: NodeID, direction: str = Direction.BOTH) -> List[Edge]:
        return self._node_edges_locked(node_id, direction)

    def has_edge(self, edge_id: EdgeID) -> bool:
        return self.kv.has(self._ek(edge_id))

    # -- counts / maintenance -------------------------------------------

    def count_nodes(self) -> int:
        return self.kv.count_prefix(b"n:")

    def count_edges(self) -> int:
        return self.kv.count_prefix(b"e:")

    def count_nodes_with_prefix(self, prefix: str) -> int:
        """O(log n + k) namespaced count via the ordered key index —
        NamespacedEngine probes for this (namespaced.py) so per-DB counts
        and quota checks don't scan the store."""
        return self.kv.count_prefix(b"n:" + prefix.encode())

    def count_edges_with_prefix(self, prefix: str) -> int:
        return self.kv.count_prefix(b"e:" + prefix.encode())

    def count_nodes_by_label(self, label: str) -> int:
        """Key-only count over the label index (no node fetches)."""
        return self.kv.count_prefix(b"l:" + label.encode() + _SEP)

    def compact(self) -> None:
        self.kv.compact()

    @property
    def repaired(self) -> int:
        """Torn-tail truncations performed during open (crash recovery)."""
        return self.kv.repaired

    def flush(self) -> None:
        self.kv.sync()

    def close(self) -> None:
        self.kv.close()
