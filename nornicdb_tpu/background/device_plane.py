"""Device-resident AI-native background plane (ISSUE 19).

ROADMAP item 3's last host loops — decay scoring (`decay.py`, one
``score()`` call per node), link prediction (`linkpredict.py`, one
Python set intersection per candidate pair), FastRP and inference
candidate generation — become amortized device passes over versioned
columnar snapshots, scheduled on the BACKGROUND admission lane
(PR 15) so a whole-graph sweep never convoys interactive traffic.

Snapshot/versioning contract (docs/background_plane.md):

- The plane keys its adjacency state on the catalog's **per-etype
  delta generations** (``ColumnarCatalog.etype_versions``): a write to
  edge type A re-extracts only A's slice; B's cached arrays — and any
  device snapshot keyed on B — stay live. The union CSR (link
  prediction's candidate graph spans every etype, matching the host
  ``AdjacencySnapshot``) rebuilds from the cached slices.
- Every job re-checks its snapshot key after the dispatch returns; a
  write that landed mid-job degrades the job to the host path via the
  audit ledger (reason ``stale_snapshot``), never a stale answer.

Host-parity contract — the device path is bit/rank-identical or it
does not serve:

- **decay**: verdicts inside the f32 score band around the archive
  threshold are re-scored on the host in f64 from the PRE-sweep Kalman
  state; outside the band f32-vs-f64 cannot flip the comparison.
- **link prediction**: the device program returns a coarse top-``op``
  superset plus the exact distinct-candidate count; kept candidates
  are re-scored through the SAME host scorer over the SAME shared
  ``AdjacencySnapshot`` the host path uses (bitwise-identical sums),
  and the seed degrades to the full host path whenever an excluded
  candidate could reach the cut (reason ``exactness``).
- **FastRP**: same algorithm, host-identical random init; f32
  accumulation order differs, so parity is tolerance-level (the brute
  index consumer is cosine-based) — documented, not silent.
- **inference candidates**: the batch rides the existing ANN service,
  so parity holds by construction.
"""

from __future__ import annotations

import os
import threading
import time
from bisect import insort as _insort
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from nornicdb_tpu import admission as _adm
from nornicdb_tpu import linkpredict as _lp
from nornicdb_tpu.obs import declare_kind, record_dispatch
from nornicdb_tpu.obs import audit as _audit
from nornicdb_tpu.obs import cost as _cost
from nornicdb_tpu.obs.metrics import REGISTRY
from nornicdb_tpu.search.microbatch import pow2_bucket
from nornicdb_tpu.storage.types import now_ms

_JOBS_C = REGISTRY.counter(
    "nornicdb_background_jobs_total",
    "Background device-plane jobs by job and outcome",
    labels=("job", "outcome"))

# dispatch kinds pre-registered so compile-cache accounting carries
# their series from the first dispatch (device_graph precedent)
KIND_DECAY = "bg_decay_sweep"
KIND_LINKPREDICT = "bg_linkpredict"
KIND_FASTRP = "bg_fastrp"
for _k in (KIND_DECAY, KIND_LINKPREDICT, KIND_FASTRP):
    declare_kind(_k)

TIER_BACKGROUND = "background_device"

# full-coverage 2-hop expansion bound: above this the dispatch is
# refused (degrade to host), never truncated — truncation would break
# the completeness the parity proof rests on
_MAX_EXPANSION = 1 << 18
# f32 decay scores within this distance of the archive threshold are
# re-scored on the host in f64 (the f32 arithmetic error on these
# O(1)-magnitude scores is < 1e-6; the band is 100x that)
_DECAY_EPS = 1e-4

_DEVICE_SCORERS = ("common_neighbors", "adamic_adar",
                   "resource_allocation")

# host-side slice width between cooperative yields: a few ms of Python
# loop work — matching the floor the CPU backend's inline kernel
# execution already imposes on the worst-case handoff, so slicing
# finer would only slow the sweep without improving the tail
_TICK_EVERY = 4096


def _bg_tick() -> None:
    """Cooperative GIL handoff between background work slices. The
    plane's contract is that a whole-graph sweep never convoys the
    interactive lane, and CPython's preemption alone does not deliver
    it: ``sleep(0)`` lets the releasing thread win the re-acquire
    race, so a waiting interactive request still waits out the full
    switch interval. A real (micro) sleep blocks this thread and
    forces the handoff; at one tick per ~1ms slice the sweep donates
    well under 10% of its runtime to the interactive lane."""
    time.sleep(50e-6)


def _ledger(reason: str,
            versions: "Dict[str, Any] | None" = None) -> None:
    """Structured degrade record for a background-device -> host step."""
    _audit.record_degrade("background", TIER_BACKGROUND, "host", reason,
                          index="background_plane", versions=versions)


def demote_to_background_priority() -> "Tuple[int, int] | None":
    """Drop the calling process to the idle scheduling class.

    The no-convoy contract has two halves. In-process, ``_bg_tick``
    donates the GIL between work slices. Across processes — the shape
    the multi-process read fleet actually deploys, with interactive
    reads served from replica subprocesses — GIL handoff is moot and
    the kernel scheduler decides who runs. A whole-graph sweep at
    normal priority earns full CFS timeslices, so an interactive
    request waking on the same core waits out a multi-millisecond
    slice. ``SCHED_IDLE`` removes that wait: idle-class tasks are
    preempted immediately when any normal-priority task wakes, so the
    sweep consumes exactly the CPU nobody else wants.

    Returns the previous ``(policy, nice)`` so a caller that demotes a
    shared process (rather than a dedicated background worker) can try
    to restore it — raising priority back needs CAP_SYS_NICE, so the
    restore is best-effort. Returns None when the platform has no
    scheduling classes (non-Linux); callers proceed undemoted and the
    cooperative ticks remain the only mitigation."""
    try:
        prev = (os.sched_getscheduler(0), os.nice(0))
        os.sched_setscheduler(0, os.SCHED_IDLE, os.sched_param(0))
        return prev
    except (AttributeError, OSError):
        try:
            os.nice(19)
            return None
        except OSError:
            return None


def bg_device_mode() -> str:
    """NORNICDB_BG_DEVICE: off | auto | on. Background jobs are not
    request-path hot functions, so the env read happens per job. The
    vectorized pass beats the per-node Python loop even on the CPU
    backend (it replaces interpreter dispatch, not just FLOPs), so
    ``auto`` engages everywhere a backend exists."""
    mode = os.environ.get("NORNICDB_BG_DEVICE", "auto").lower()
    return mode if mode in ("off", "auto", "on") else "auto"


def _jx():
    import jax

    return jax


class BackgroundDevicePlane:
    """Background-lane device jobs over per-etype delta snapshots.

    Constructed next to a ``ColumnarCatalog``; optionally wired to a
    ``DecayManager`` (whose ``sweep()`` then routes here first) and an
    ``InferenceEngine`` (whose ``on_store_batch`` rides the plane's
    background lane)."""

    def __init__(self, storage, catalog, decay=None, inference=None):
        self.storage = storage
        self.catalog = catalog
        self.decay = decay
        self.inference = inference
        self._lock = threading.Lock()
        # etype -> {"etv": (struct_gen, gen), "src": np, "dst": np}
        self._slices: Dict[str, Dict[str, Any]] = {}
        self._union: Optional[Dict[str, Any]] = None
        self.dispatches = 0
        if decay is not None:
            decay.device_plane = self
        if inference is not None:
            inference.device_plane = self

    # -- per-etype delta slices -------------------------------------------

    def _etype_slice(self, et: str) -> Optional[Dict[str, Any]]:
        """This etype's edge arrays, cached on its delta key: a write
        to a DIFFERENT etype leaves this slice (and its cached copy)
        untouched — the re-extraction cost tracks the changed slice,
        not the graph."""
        cat = self.catalog
        etv = cat.etype_version(et)
        with self._lock:
            sl = self._slices.get(et)
        if sl is not None and sl["etv"] == etv:
            return sl
        tbl = cat.edge_table(et)
        # copy: the table's views extend in place under appends
        sl = {"etv": etv,
              "src": np.asarray(tbl.src, dtype=np.int64).copy(),
              "dst": np.asarray(tbl.dst, dtype=np.int64).copy()}
        if cat.etype_version(et) != etv:
            return None  # raced a write mid-extract; caller degrades
        with self._lock:
            self._slices[et] = sl
        return sl

    def _union_snapshot(self) -> Optional[Dict[str, Any]]:
        """Deduplicated undirected union CSR over every etype slice —
        row-for-row the host ``AdjacencySnapshot`` neighbor sets, as
        sorted int arrays. Cached until ANY etype's delta key moves;
        the rebuild re-reads only the changed slices."""
        cat = self.catalog
        etypes = tuple(cat.edge_types())
        etv = cat.etype_versions(etypes)
        with self._lock:
            u = self._union
        if (u is not None and u["etypes"] == etypes
                and u["etv"] == etv):
            return u
        nodes = cat.nodes()
        n = cat.n_nodes()
        parts: List[np.ndarray] = []
        for et in etypes:
            sl = self._etype_slice(et)
            if sl is None:
                return None
            if len(sl["src"]):
                parts.append(sl["src"] * n + sl["dst"])
                parts.append(sl["dst"] * n + sl["src"])
        if parts:
            keys = np.unique(np.concatenate(parts))
            su = keys // n
            nbr = (keys % n).astype(np.int32)
        else:
            su = np.zeros(0, np.int64)
            nbr = np.zeros(0, np.int32)
        indptr = np.searchsorted(su, np.arange(n + 1)).astype(np.int32)
        deg = indptr[1:] - indptr[:-1]
        snap = {
            "etypes": etypes,
            "etv": etv,
            "version": cat.version,
            "n": n,
            "indptr": indptr,
            "nbr": nbr,
            "max_deg": int(deg.max()) if n else 0,
            "ids": [nd.id for nd in nodes],
            "row_of": {nd.id: i for i, nd in enumerate(nodes)},
            "w": {},     # method -> host f32 weight column
            "dev": None,  # lazily transferred device arrays
            "host_bytes": int(indptr.nbytes + nbr.nbytes),
        }
        if cat.etype_versions(etypes) != etv:
            return None  # node axis or an etype moved mid-build
        with self._lock:
            self._union = snap
        return snap

    def _device_arrays(self, snap: Dict[str, Any], method: str):
        from nornicdb_tpu.ops import linkpredict as _olp

        jnp = _jx().numpy
        with self._lock:
            if snap["dev"] is None:
                snap["dev"] = {
                    "indptr": jnp.asarray(snap["indptr"]),
                    "nbr": jnp.asarray(snap["nbr"]),
                    "w": {},
                }
            w = snap["w"].get(method)
            if w is None:
                w = _olp.degree_weights(method, snap["indptr"])
                snap["w"][method] = w
            dw = snap["dev"]["w"].get(method)
            if dw is None:
                dw = jnp.asarray(w)
                snap["dev"]["w"][method] = dw
        return snap["dev"]["indptr"], snap["dev"]["nbr"], dw, w

    def resource_stats(self) -> Dict[str, float]:
        with self._lock:
            u = self._union
        if u is None:
            return {"device_bytes": 0, "host_bytes": 0, "rows": 0,
                    "mutation_gap": 0}
        return {
            "device_bytes": (u["host_bytes"]
                             if u["dev"] is not None else 0),
            "host_bytes": u["host_bytes"],
            "rows": int(len(u["nbr"])),
            "mutation_gap": max(0, self.catalog.version - u["version"]),
        }

    # -- decay: one vmapped score-and-verdict pass ------------------------

    def decay_sweep(self, now: Optional[int] = None
                    ) -> Optional[Tuple[int, int]]:
        """Whole-graph decay sweep as ONE device dispatch. Returns
        (scored, archived) with verdicts identical to the host sweep,
        or None (caller runs the host loop). Verdicts are applied back
        through the normal storage write path; Kalman state is written
        back in f32 (the documented device-plane contract — the
        comparison band around the threshold is re-scored in f64)."""
        dm = self.decay
        if dm is None or bg_device_mode() == "off":
            return None
        from nornicdb_tpu.ops import decay as _od

        with _adm.lane_scope(_adm.LANE_BACKGROUND):
            t_all = time.perf_counter()
            v0 = self.catalog.version
            now = now if now is not None else now_ms()
            # the catalog's resident node snapshot, NOT
            # storage.all_nodes(): the host loop's O(N) defensive node
            # copies are most of its sweep cost, and the catalog
            # version re-check below is what makes skipping them safe
            try:
                nodes = self.catalog.nodes()
            except Exception:  # noqa: BLE001 — storage gone: host path
                _ledger("error")
                _JOBS_C.labels("decay_sweep", "degraded").inc()
                return None
            m = len(nodes)
            if m == 0:
                _JOBS_C.labels("decay_sweep", "device").inc()
                return (0, 0)
            from nornicdb_tpu.filters import KalmanFilter as _KF

            q = _KF.process_noise
            r = _KF.measurement_noise
            # column extraction: plain lists + one bulk np conversion
            # (per-element ndarray stores are ~4x slower); exact f64
            # values survive in the lists for the boundary-band check
            ages: List[float] = []
            hls: List[float] = []
            cnts: List[float] = []
            imps: List[float] = []
            ests: List[float] = []
            errs: List[float] = []
            inits: List[bool] = []
            kfs: List[Any] = []
            ap_age = ages.append
            ap_hl = hls.append
            ap_cnt = cnts.append
            ap_imp = imps.append
            ap_est = ests.append
            ap_err = errs.append
            ap_init = inits.append
            ap_kf = kfs.append
            half = dm.half_life_ms
            use_kalman = dm.use_kalman
            with dm._lock:
                states = dm._state
                st_get = states.get
                seen = 0
                for node in nodes:
                    seen += 1
                    if not (seen % _TICK_EVERY):
                        _bg_tick()
                    nid = node.id
                    st = st_get(nid)
                    if st is None:
                        st = _new_node_state()
                        states[nid] = st
                    last = (st.last_access_ms or node.updated_at
                            or node.created_at or now)
                    a = now - last
                    ap_age(a if a > 0 else 0)
                    ap_hl(half[st.tier])
                    ap_cnt(st.access_count)
                    try:
                        iv = float(node.properties.get(
                            "importance", 0.5))
                    except (TypeError, ValueError):
                        iv = 0.5
                    ap_imp(0.0 if iv < 0.0 else
                           (1.0 if iv > 1.0 else iv))
                    k = st.kalman
                    ap_est(k.estimate)
                    ap_err(k.error)
                    ap_init(k.initialized and use_kalman)
                    ap_kf(k)
            bsz = pow2_bucket(m)

            def _pad(vals, dtype, fill):
                # chunked fill: one 100k-list conversion is a multi-ms
                # C-atomic GIL hold, which the tick contract forbids
                col = np.full(bsz, fill, dtype)
                for off in range(0, m, 4 * _TICK_EVERY):
                    hi = min(off + 4 * _TICK_EVERY, m)
                    col[off:hi] = vals[off:hi]
                    _bg_tick()
                return col

            weights = (dm.w_recency, dm.w_frequency, dm.w_importance)
            t0 = time.perf_counter()
            try:
                scores, new_est, new_err = _od.decay_scores(
                    _pad(ages, np.float32, 0), _pad(hls, np.float32, 1),
                    _pad(cnts, np.float32, 0),
                    _pad(imps, np.float32, 0),
                    _pad(ests, np.float32, 0),
                    _pad(errs, np.float32, 1),
                    _pad(inits, bool, False), weights, q, r)
            except Exception:  # noqa: BLE001 — degrade, never fail
                _ledger("error")
                _JOBS_C.labels("decay_sweep", "degraded").inc()
                return None
            dt = time.perf_counter() - t0
            record_dispatch(KIND_DECAY, bsz, 0, dt)
            if _cost.pricing_enabled():
                flops, byts = _cost.price_decay_sweep(bsz)
                _cost.record_query_cost(KIND_DECAY, "background_plane",
                                        m, flops, byts)
            self.dispatches += 1
            # post-dispatch freshness: a write during the window means
            # the columns no longer describe the store — host re-runs
            if self.catalog.version != v0:
                _ledger("stale_snapshot",
                        {"snapshot_version": v0,
                         "catalog_version": self.catalog.version})
                _JOBS_C.labels("decay_sweep", "degraded").inc()
                return None
            thr = dm.archive_threshold
            scores = scores[:m].astype(np.float64)
            # verdicts inside the f32 band around the threshold are
            # re-scored in f64 from the PRE-sweep state held in the
            # extraction lists (score() would advance the live filter
            # a second time — decay_score_host_f64 is pure)
            for i in np.nonzero(
                    np.abs(scores - thr) < _DECAY_EPS)[0].tolist():
                scores[i] = _od.decay_score_host_f64(
                    ages[i], hls[i], cnts[i], imps[i], ests[i],
                    errs[i], inits[i], weights, q, r)
            if use_kalman:
                for off in range(0, m, _TICK_EVERY):
                    hi = min(off + _TICK_EVERY, m)
                    ne = new_est[off:hi].tolist()
                    nv = new_err[off:hi].tolist()
                    with dm._lock:
                        for k, e, v in zip(kfs[off:hi], ne, nv):
                            k.estimate = e
                            k.error = v
                            k.initialized = True
                    _bg_tick()
            archived = 0
            # archive through the normal write path, on FRESH storage
            # copies (never the catalog's resident objects — pushing
            # those back could clobber fields written since the build)
            for t, i in enumerate(np.nonzero(scores < thr)[0].tolist()):
                if t and not (t % _TICK_EVERY):
                    _bg_tick()
                try:
                    node = dm.storage.get_node(nodes[i].id)
                except KeyError:
                    continue
                if node.properties.get("_archived"):
                    continue
                node.properties["_archived"] = True
                node.properties["_archived_at"] = now
                try:
                    dm.storage.update_node(node)
                    archived += 1
                except KeyError:
                    pass
            _audit.record_served("background", TIER_BACKGROUND,
                                 time.perf_counter() - t_all)
            _JOBS_C.labels("decay_sweep", "device").inc()
            return (m, archived)

    # -- link prediction: masked sparse expansion + top-k -----------------

    def linkpredict_topk(
        self,
        seeds: Sequence[str],
        method: str = "adamic_adar",
        limit: int = 10,
    ) -> Optional[Dict[str, List[Tuple[str, float]]]]:
        """Top-``limit`` predicted links for a batch of seed nodes in
        ONE device program, result-identical to per-seed host
        ``predict_links``. Returns None when the whole batch must run
        on the host (mode off / unsupported scorer / stale snapshot);
        individual seeds whose exactness cannot be PROVEN degrade to
        the host path inline, so the returned dict is always complete
        and always right."""
        if bg_device_mode() == "off" or method not in _DEVICE_SCORERS:
            return None
        from nornicdb_tpu.ops import linkpredict as _olp

        with _adm.lane_scope(_adm.LANE_BACKGROUND):
            t_all = time.perf_counter()
            snap = self._union_snapshot()
            if snap is None:
                _ledger("stale_snapshot",
                        {"catalog_version": self.catalog.version})
                _JOBS_C.labels("linkpredict", "degraded").inc()
                return None
            n = snap["n"]
            indptr = snap["indptr"]
            row_of = snap["row_of"]
            rows = [row_of.get(sid, -1) for sid in seeds]
            f2 = pow2_bucket(max(1, snap["max_deg"]))
            op = pow2_bucket(max(2 * limit, 32))
            # seed-degree bucketing: kernel time is linear in the
            # padded expansion f1*f2, so seeds dispatch in groups
            # sized to their OWN 1-hop width, not the batch max
            groups: Dict[int, List[int]] = {}
            host_set: set = set()
            for i, r in enumerate(rows):
                if r < 0:
                    continue
                deg = int(indptr[r + 1] - indptr[r])
                f1g = max(8, pow2_bucket(max(1, deg)))
                if f1g * f2 > _MAX_EXPANSION:
                    # full coverage will not fit: this seed is refused
                    # (never truncated) and served by the host path
                    host_set.add(i)
                else:
                    groups.setdefault(f1g, []).append(i)
            if host_set:
                _ledger("overflow", {"snapshot_etv": snap["etv"]})
            dip, dnbr, dw, w_host = self._device_arrays(snap, method)
            # seed index -> (vals_kept, rows_kept, covered, rawmin, f1g)
            per: Dict[int, Tuple] = {}
            for f1g in sorted(groups):
                idxs = groups[f1g]
                kpg = min(op, f1g * f2)
                bszg = pow2_bucket(len(idxs))
                seed_rows = np.full(bszg, -1, np.int32)
                seed_rows[:len(idxs)] = [rows[i] for i in idxs]
                t0 = time.perf_counter()
                try:
                    vals, sel, distinct = _olp.linkpredict_topk(
                        seed_rows, dip, dnbr, dw, n, f1g, f2, kpg)
                except Exception:  # noqa: BLE001 — degrade, not fail
                    _ledger("error", {"snapshot_etv": snap["etv"]})
                    _JOBS_C.labels("linkpredict", "degraded").inc()
                    return None
                dt = time.perf_counter() - t0
                record_dispatch(KIND_LINKPREDICT, bszg,
                                f1g * 100_000 + kpg, dt)
                if _cost.pricing_enabled():
                    flops, byts = _cost.price_linkpredict(
                        bszg, f1g, f2, kpg)
                    _cost.record_query_cost(KIND_LINKPREDICT,
                                            "background_plane",
                                            len(idxs), flops, byts)
                self.dispatches += 1
                for j, i in enumerate(idxs):
                    row = vals[j]
                    keep = np.isfinite(row) & (row > 0)
                    covered = int(distinct[j]) <= kpg
                    # when candidates were excluded, the coverage
                    # guard needs the TRUE smallest kept device score
                    # (including zero-score slots the > 0 filter
                    # drops) — excluded candidates sit at or below it
                    rawmin = 0.0 if covered else float(row.min())
                    per[i] = (row[keep], sel[j][keep], covered,
                              rawmin, f1g)
            # per-etype post-dispatch recheck: only a write touching
            # one of the snapshot's etypes (or the node axis) landed
            # mid-dispatch stales this — the delta-snapshot payoff
            if self.catalog.etype_versions(
                    snap["etypes"]) != snap["etv"]:
                _ledger("stale_snapshot",
                        {"snapshot_etv": snap["etv"],
                         "catalog_version": self.catalog.version})
                _JOBS_C.labels("linkpredict", "degraded").inc()
                return None
            is_cn = method == "common_neighbors"
            wmax = float(w_host.max(initial=0.0))
            hsnap = None
            scorer = _lp.SCORERS[method]
            ids = snap["ids"]
            out: Dict[str, List[Tuple[str, float]]] = {}
            degraded = 0
            unproven = 0
            for i, sid in enumerate(seeds):
                if i and not (i % 32):
                    _bg_tick()  # finalize is ~0.1ms/seed of host work
                if rows[i] < 0:
                    out[sid] = []  # unknown node: host returns [] too
                    continue
                if i in host_set:
                    out[sid] = _lp.predict_links(
                        self.storage, sid, method=method,
                        limit=limit, catalog=self.catalog)
                    degraded += 1
                    continue
                dvals, crows, covered, rawmin, f1g = per[i]
                dl = dvals.tolist()
                if is_cn:
                    # counts are integer-exact in f32: the device
                    # values ARE the host float scores — no re-score
                    res = [(ids[cr], dv) for cr, dv
                           in zip(crows.tolist(), dl)]
                    res.sort(key=lambda kv: (-kv[1], kv[0]))
                    result = res[:limit]
                    safe = covered or (len(result) >= limit
                                       and rawmin < result[-1][1])
                    if not safe:
                        out[sid] = _lp.predict_links(
                            self.storage, sid, method=method,
                            limit=limit, catalog=self.catalog)
                        degraded += 1
                        unproven += 1
                        continue
                    out[sid] = result
                    continue
                # weighted scorers: exact host re-score through the
                # SHARED snapshot (bitwise the host path's f64 sums),
                # lazily in device-rank order — once ``limit`` exact
                # scores are in hand and the next device value plus
                # the f32 accumulation bound cannot reach the cut,
                # no remaining candidate can either
                werr = 4.8e-7 * f1g * wmax
                if hsnap is None:
                    hsnap = _lp.adjacency_snapshot(
                        self.storage, self.catalog)
                ex: List[Tuple[float, str]] = []  # asc (-score, id)
                cut = None
                for t, cr in enumerate(crows.tolist()):
                    if cut is not None and dl[t] + werr < cut:
                        break
                    c = ids[cr]
                    s = scorer(hsnap, sid, c)
                    if s > 0:
                        _insort(ex, (-s, c))
                        if len(ex) >= limit:
                            cut = -ex[limit - 1][0]
                result = [(c, -ns) for ns, c in ex[:limit]]
                safe = covered or (cut is not None
                                   and rawmin + werr < cut)
                if not safe:
                    out[sid] = _lp.predict_links(
                        self.storage, sid, method=method,
                        limit=limit, catalog=self.catalog)
                    degraded += 1
                    unproven += 1
                    continue
                out[sid] = result
            if unproven:
                _ledger("exactness", {"snapshot_etv": snap["etv"]})
            if degraded:
                _JOBS_C.labels("linkpredict", "partial").inc()
            else:
                _JOBS_C.labels("linkpredict", "device").inc()
            _audit.record_served("background", TIER_BACKGROUND,
                                 time.perf_counter() - t_all)
            return out

    # -- FastRP: device matmul chain over the union CSR -------------------

    def fastrp(self, dim: int = 64,
               iteration_weights: Sequence[float] = (0.0, 1.0, 1.0),
               normalization_strength: float = 0.0,
               seed: int = 42
               ) -> Optional[Tuple[List[str], np.ndarray]]:
        """FastRP embeddings for the whole union graph on device,
        feeding the brute index. Returns (node_ids, [n, dim] f32) or
        None (host ``ops.fastrp.fastrp_embeddings`` serves). Same
        algorithm, host-identical init; f32 accumulation makes this a
        tolerance-parity surface (see module docstring)."""
        if bg_device_mode() == "off":
            return None
        from nornicdb_tpu.ops import fastrp as _ofr

        with _adm.lane_scope(_adm.LANE_BACKGROUND):
            t_all = time.perf_counter()
            snap = self._union_snapshot()
            if snap is None:
                _ledger("stale_snapshot",
                        {"catalog_version": self.catalog.version})
                _JOBS_C.labels("fastrp", "degraded").inc()
                return None
            # propagation runs over the directed edge list exactly as
            # the host does (both directions inside the kernel); the
            # deduped union rows ARE that list here — each undirected
            # pair once
            pairs_src = np.repeat(
                np.arange(snap["n"], dtype=np.int32),
                snap["indptr"][1:] - snap["indptr"][:-1])
            pairs_dst = snap["nbr"]
            half = pairs_src < pairs_dst
            loops = pairs_src == pairs_dst
            src = np.concatenate([pairs_src[half], pairs_src[loops]])
            dst = np.concatenate([pairs_dst[half], pairs_dst[loops]])
            t0 = time.perf_counter()
            try:
                emb = _ofr.fastrp_embeddings_device(
                    snap["n"], src, dst, dim=dim,
                    iteration_weights=iteration_weights,
                    normalization_strength=normalization_strength,
                    seed=seed)
            except Exception:  # noqa: BLE001 — degrade, never fail
                _ledger("error", {"snapshot_etv": snap["etv"]})
                _JOBS_C.labels("fastrp", "degraded").inc()
                return None
            dt = time.perf_counter() - t0
            record_dispatch(KIND_FASTRP, pow2_bucket(max(1, snap["n"])),
                            pow2_bucket(max(1, dim)), dt)
            if _cost.pricing_enabled():
                flops, byts = _cost.price_fastrp(
                    snap["n"], len(src), dim,
                    len(tuple(iteration_weights)))
                _cost.record_query_cost(KIND_FASTRP, "background_plane",
                                        max(1, snap["n"]), flops, byts)
            self.dispatches += 1
            if self.catalog.etype_versions(
                    snap["etypes"]) != snap["etv"]:
                _ledger("stale_snapshot",
                        {"snapshot_etv": snap["etv"],
                         "catalog_version": self.catalog.version})
                _JOBS_C.labels("fastrp", "degraded").inc()
                return None
            _audit.record_served("background", TIER_BACKGROUND,
                                 time.perf_counter() - t_all)
            _JOBS_C.labels("fastrp", "device").inc()
            return (snap["ids"], emb)

    # -- inference candidate generation -----------------------------------

    def infer_candidates(
        self, items: Sequence[Tuple[str, Sequence[float]]], k: int,
    ) -> Optional[Dict[str, List[Tuple[str, float]]]]:
        """Batched ANN candidate generation for newly stored nodes:
        rides the existing quantized ANN tiers (the search service's
        own serving ladder) under the background lane instead of
        per-node exact scans on the interactive path. Parity holds by
        construction — the candidates come from the same service the
        per-node path calls."""
        inf = self.inference
        if inf is None or inf.search is None \
                or bg_device_mode() == "off":
            return None
        with _adm.lane_scope(_adm.LANE_BACKGROUND):
            out: Dict[str, List[Tuple[str, float]]] = {}
            try:
                for nid, vec in items:
                    out[nid] = list(
                        inf.search.vector_search_candidates(vec, k=k))
            except Exception:  # noqa: BLE001 — degrade, never fail
                _ledger("error")
                _JOBS_C.labels("infer_candidates", "degraded").inc()
                return None
            _JOBS_C.labels("infer_candidates", "device").inc()
            return out


def _new_node_state():
    from nornicdb_tpu.decay import _NodeState

    return _NodeState()
