"""Device-resident background plane (ISSUE 19): decay, link
prediction, FastRP and inference candidate generation as background-
lane device jobs over per-etype delta snapshots."""

from nornicdb_tpu.background.device_plane import (  # noqa: F401
    BackgroundDevicePlane,
    bg_device_mode,
)
