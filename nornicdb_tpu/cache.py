"""Generic LRU+TTL cache with write-generation invalidation.

Reference: pkg/cache/query_cache.go (LRU+TTL query result cache) and its
use by the Cypher read-cache probe (pkg/cypher/executor.go:634) with
invalidation on writes (cache_policy.go).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Generic, Hashable, Optional, Tuple, TypeVar

V = TypeVar("V")


class LRUCache(Generic[V]):
    """Thread-safe LRU with per-entry TTL and hit/miss stats."""

    def __init__(self, max_size: int = 1024, ttl_seconds: float = 0.0):
        self.max_size = max(1, max_size)
        self.ttl = ttl_seconds
        self._data: "OrderedDict[Hashable, Tuple[V, float]]" = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    def get(self, key: Hashable, default: Optional[V] = None) -> Optional[V]:
        now = time.monotonic()
        with self._lock:
            item = self._data.get(key)
            if item is None:
                self.misses += 1
                return default
            value, expires = item
            if expires and now > expires:
                del self._data[key]
                self.misses += 1
                return default
            self._data.move_to_end(key)
            self.hits += 1
            return value

    def put(self, key: Hashable, value: V, ttl_seconds: Optional[float] = None) -> None:
        ttl = self.ttl if ttl_seconds is None else ttl_seconds
        expires = time.monotonic() + ttl if ttl else 0.0
        with self._lock:
            self._put_locked(key, value, expires)

    def _put_locked(self, key: Hashable, value: V, expires: float) -> None:
        self._data[key] = (value, expires)
        self._data.move_to_end(key)
        while len(self._data) > self.max_size:
            self._data.popitem(last=False)

    def delete(self, key: Hashable) -> bool:
        with self._lock:
            return self._data.pop(key, None) is not None

    def clear(self) -> None:
        with self._lock:
            self._data.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._data)

    def stats(self) -> dict:
        with self._lock:
            total = self.hits + self.misses
            return {
                "size": len(self._data), "max_size": self.max_size,
                "hits": self.hits, "misses": self.misses,
                "hit_rate": self.hits / total if total else 0.0,
            }

    def get_or_compute(self, key: Hashable, compute: Callable[[], V],
                       ttl_seconds: Optional[float] = None) -> V:
        sentinel = object()
        v = self.get(key, sentinel)  # type: ignore[arg-type]
        if v is not sentinel:
            return v  # type: ignore[return-value]
        value = compute()
        self.put(key, value, ttl_seconds)
        return value


class GenerationalCache(LRUCache[V]):
    """LRU+TTL cache whose entries are invalidated wholesale by bumping a
    write generation — the Cypher read-cache policy (reference:
    cache_policy.go: any write invalidates cached read results)."""

    def __init__(self, max_size: int = 1024, ttl_seconds: float = 0.0):
        super().__init__(max_size, ttl_seconds)
        self._generation = 0
        # optional write-through mirror: the multi-worker wire plane
        # (ISSUE 11) publishes the generation into shared memory so
        # frontend workers in OTHER processes validate their wire
        # caches against the live value without a broker round trip
        self._gen_mirror = None

    def set_generation_mirror(self, fn) -> None:
        """``fn(generation)`` invoked on every bump (and once at
        registration with the current value). Pass None to detach."""
        self._gen_mirror = fn
        if fn is not None:
            try:
                fn(self.generation)
            except Exception:  # noqa: BLE001 — mirror must not break writes
                pass

    def bump_generation(self) -> None:
        with self._lock:
            self._generation += 1
            self._data.clear()
            # publish under the SAME lock: two racing bumps must hit
            # the mirror in generation order, or the shared-memory
            # value could regress and validate stale worker entries
            if self._gen_mirror is not None:
                try:
                    self._gen_mirror(self._generation)
                except Exception:  # noqa: BLE001 — never break writes
                    pass

    @property
    def generation(self) -> int:
        with self._lock:
            return self._generation


class WireCache:
    """Shared serialized-response cache for the protocol surfaces.

    Keyed (method, request bytes) -> (generation, response bytes). A hit
    is valid only when the caller's CURRENT generation for that method
    family matches the generation recorded at put time — the same
    write-generation discipline as ``GenerationalCache``, except the
    generation counters live with the data planes (QdrantCompat's search
    cache, SearchService's result cache), each fed by the storage
    mutation listeners wired in db.py. One instance serves every wire
    method of a server, so the hot handlers do ZERO protobuf/JSON work
    on a hit: request bytes in, response bytes out.

    Entries are immutable bytes — no copy-on-return hook is needed
    (unlike ResultCache, whose hits share nested dicts with live nodes).

    Telemetry: hit/miss/invalidation counters under
    ``nornicdb_wire_cache_*_total{cache=<name>}`` — per cache NAME, so
    two instances constructed with the same name share one series. An
    "invalidation" is a generation-mismatch probe — the entry was
    present but a write on some surface outdated it (the generation
    counters live with the data planes, so the mismatch at get() is
    where staleness becomes observable).
    """

    def __init__(self, max_size: int = 2048, ttl_seconds: float = 300.0,
                 name: str = "wire"):
        from nornicdb_tpu.obs import REGISTRY

        self._lru: LRUCache = LRUCache(max_size=max_size,
                                       ttl_seconds=ttl_seconds)
        self.name = name
        self._hits_c = REGISTRY.counter(
            "nornicdb_wire_cache_hits_total",
            "Wire-cache hits (serialized response served)",
            labels=("cache",)).labels(name)
        self._misses_c = REGISTRY.counter(
            "nornicdb_wire_cache_misses_total",
            "Wire-cache misses (response computed + serialized)",
            labels=("cache",)).labels(name)
        self._inval_c = REGISTRY.counter(
            "nornicdb_wire_cache_invalidations_total",
            "Wire-cache entries found stale (generation mismatch)",
            labels=("cache",)).labels(name)

    def get(self, method: str, data: bytes, gen: int) -> Optional[bytes]:
        hit = self._lru.get((method, data))
        if hit is not None and hit[0] == gen:
            self._hits_c.inc()
            return hit[1]
        if hit is not None:
            # present but outdated by a write: the observable moment of
            # invalidation (entries are never proactively swept)
            self._inval_c.inc()
        self._misses_c.inc()
        return None

    def put(self, method: str, data: bytes, gen: int,
            payload: bytes) -> None:
        # gen was sampled BEFORE the compute; a write that raced the
        # compute bumped the live generation, so the stale entry can
        # never validate on get() — no put-side guard needed.
        self._lru.put((method, data), (gen, payload))

    def stats(self) -> dict:
        # wire_* come from the lock-striped registry counters (exact
        # under racing gets) and cover every instance sharing this name
        return {**self._lru.stats(),
                "wire_hits": self._hits_c.value,
                "wire_misses": self._misses_c.value,
                "wire_invalidations": self._inval_c.value}

    def clear(self) -> None:
        self._lru.clear()


class ResultCache(GenerationalCache[list]):
    """Search-result cache with the reference searchResultCache
    semantics (search.go:88-92: LRU 1000, 5-min TTL, invalidated on any
    index mutation), hardened two ways:

    - generation-guarded puts: a compute that read pre-write state and
      raced an invalidation must not pin its stale result for the TTL
      (the guard and the insert run under ONE lock acquisition);
    - a per-hit copy hook applied on every get/put return, so callers
      can never mutate a cached entry (hits often share nested dicts
      with live nodes by reference).

    One implementation carries the search service and the qdrant layer;
    the gRPC wire cache validates its raw-bytes entries against
    ``generation``.
    """

    def __init__(self, copy_hit: Callable[[Any], Any],
                 max_size: int = 1000, ttl_seconds: float = 300.0):
        super().__init__(max_size, ttl_seconds)
        self._copy_hit = copy_hit

    def get_hits(self, key: Hashable) -> Optional[list]:
        hits = self.get(key)
        if hits is None:
            return None
        return [self._copy_hit(h) for h in hits]

    def put_guarded(self, key: Hashable, hits: list,
                    gen_at_miss: int) -> list:
        """Insert unless an invalidation raced the compute. Returns
        caller-safe copies either way."""
        expires = time.monotonic() + self.ttl if self.ttl else 0.0
        with self._lock:
            if self._generation == gen_at_miss:
                self._put_locked(key, hits, expires)
        return [self._copy_hit(h) for h in hits]
