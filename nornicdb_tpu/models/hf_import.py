"""Real-weight import path: HuggingFace encoder checkpoints → flax.

The reference ships working bge-m3 inference over vendored llama.cpp
(pkg/embed/local_gguf.go:57,100 LocalGGUFEmbedder). This image has no
network, so real bge-m3 weights are unreachable — but the import path
must exist so that the day a checkpoint IS reachable it is "drop in
weights, done". This module provides:

- ``HFEncoder``: a flax module that reproduces the BERT/RoBERTa
  (XLM-R = RoBERTa arch, bge-m3's backbone) computation graph exactly
  — post-LayerNorm blocks, token-type embeddings, erf GELU, RoBERTa's
  pad-offset position ids — so imported weights produce the same
  embeddings the published model does (validated numerically against
  ``transformers``' torch implementation in
  tests/test_hf_import.py).
- ``import_hf_params``: state-dict name mapping (works for
  ``bert.*`` / ``roberta.*`` / bare prefixes, safetensors or
  torch .bin or npz).
- ``load_hf_model_dir``: one-call load of a local HF model directory
  (config.json + model.safetensors [+ tokenizer files]).
- ``HFEncoderEmbedder``: embed_batch over the imported model with the
  model's own tokenizer (AutoTokenizer from local files; never
  downloads).

Set ``NORNICDB_TPU_MODEL_DIR=/path/to/model`` to make an imported
model the DB's default embedder (db.DB._default_embedder checks
``default_model_dir()`` ahead of the committed mini encoder;
``NORNICDB_TPU_EMBEDDER=hash`` still force-overrides everything).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class HFEncoderConfig:
    vocab_size: int
    hidden_size: int
    num_layers: int
    num_heads: int
    intermediate_size: int
    max_position_embeddings: int
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    pad_token_id: int = 0
    # 'bert' = arange position ids; 'roberta' (XLM-R, bge-m3 backbone) =
    # cumsum-of-mask ids offset past the padding idx
    arch: str = "bert"
    pooling: str = "mean"  # 'mean' | 'cls'
    dtype: Any = jnp.float32

    @staticmethod
    def from_hf_config(cfg: Dict[str, Any]) -> "HFEncoderConfig":
        model_type = cfg.get("model_type", "bert")
        arch = "roberta" if model_type in (
            "roberta", "xlm-roberta", "camembert") else "bert"
        return HFEncoderConfig(
            vocab_size=int(cfg["vocab_size"]),
            hidden_size=int(cfg["hidden_size"]),
            num_layers=int(cfg["num_hidden_layers"]),
            num_heads=int(cfg["num_attention_heads"]),
            intermediate_size=int(cfg["intermediate_size"]),
            max_position_embeddings=int(cfg["max_position_embeddings"]),
            type_vocab_size=int(cfg.get("type_vocab_size", 2)),
            layer_norm_eps=float(cfg.get("layer_norm_eps", 1e-12)),
            pad_token_id=int(cfg.get("pad_token_id", 0) or 0),
            arch=arch,
        )


class HFEncoder(nn.Module):
    """BERT/RoBERTa-faithful encoder: token ids -> pooled embedding.

    Post-LN residual blocks (unlike models.encoder.Encoder, which is
    pre-LN by design for from-scratch TPU training) — faithfulness is
    the point here: published weights assume this exact graph."""

    cfg: HFEncoderConfig

    @nn.compact
    def __call__(
        self,
        token_ids: jnp.ndarray,
        attention_mask: Optional[jnp.ndarray] = None,
    ) -> jnp.ndarray:
        cfg = self.cfg
        if attention_mask is None:
            attention_mask = (token_ids != cfg.pad_token_id)
        mask = attention_mask.astype(jnp.int32)
        if cfg.arch == "roberta":
            # RoBERTa position ids: running count of non-pad tokens,
            # shifted past the padding index (HF create_position_ids_
            # from_input_ids semantics)
            positions = jnp.cumsum(mask, axis=1) * mask + cfg.pad_token_id
        else:
            positions = jnp.broadcast_to(
                jnp.arange(token_ids.shape[1])[None, :], token_ids.shape
            )
        x = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                     name="tok_embed")(token_ids)
        x = x + nn.Embed(cfg.max_position_embeddings, cfg.hidden_size,
                         dtype=cfg.dtype, name="pos_embed")(positions)
        x = x + nn.Embed(max(cfg.type_vocab_size, 1), cfg.hidden_size,
                         dtype=cfg.dtype, name="type_embed")(
            jnp.zeros_like(token_ids))
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=jnp.float32,
                         name="emb_ln")(x)
        neg = jnp.finfo(jnp.float32).min
        bias = jnp.where(attention_mask[:, None, None, :], 0.0, neg)
        head_dim = cfg.hidden_size // cfg.num_heads
        scale = head_dim ** -0.5
        for i in range(cfg.num_layers):
            pre = f"layer_{i}"
            q = nn.Dense(cfg.hidden_size, dtype=cfg.dtype,
                         name=f"{pre}_q")(x)
            k = nn.Dense(cfg.hidden_size, dtype=cfg.dtype,
                         name=f"{pre}_k")(x)
            v = nn.Dense(cfg.hidden_size, dtype=cfg.dtype,
                         name=f"{pre}_v")(x)

            def heads(t):
                return t.reshape(t.shape[0], t.shape[1],
                                 cfg.num_heads, head_dim)

            logits = jnp.einsum("bqhd,bkhd->bhqk", heads(q), heads(k))
            logits = logits * scale + bias
            w = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
            a = jnp.einsum("bhqk,bkhd->bqhd", w.astype(cfg.dtype), heads(v))
            a = a.reshape(a.shape[0], a.shape[1], cfg.hidden_size)
            a = nn.Dense(cfg.hidden_size, dtype=cfg.dtype,
                         name=f"{pre}_o")(a)
            x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=jnp.float32,
                             name=f"{pre}_attn_ln")(x + a)
            m = nn.Dense(cfg.intermediate_size, dtype=cfg.dtype,
                         name=f"{pre}_mlp_up")(x)
            m = nn.gelu(m, approximate=False)  # HF 'gelu' is erf-based
            m = nn.Dense(cfg.hidden_size, dtype=cfg.dtype,
                         name=f"{pre}_mlp_down")(m)
            x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=jnp.float32,
                             name=f"{pre}_mlp_ln")(x + m)
        if cfg.pooling == "cls":
            pooled = x[:, 0, :].astype(jnp.float32)
        else:
            m = attention_mask[:, :, None].astype(jnp.float32)
            pooled = jnp.sum(x.astype(jnp.float32) * m, axis=1) / jnp.maximum(
                jnp.sum(m, axis=1), 1.0)
        from nornicdb_tpu.ops.similarity import l2_normalize

        return l2_normalize(pooled)


# -- state-dict import -----------------------------------------------------

_PREFIXES = ("bert.", "roberta.", "model.", "encoder.model.", "")


def _strip_prefix(names: Sequence[str]) -> str:
    for pre in _PREFIXES:
        if pre and sum(1 for n in names if n.startswith(pre)) > len(names) // 2:
            return pre
    return ""


def import_hf_params(
    tensors: Dict[str, np.ndarray], cfg: HFEncoderConfig
) -> Dict[str, Any]:
    """Map a HF BERT/RoBERTa state dict onto HFEncoder's param tree.

    ``tensors``: name -> array (from safetensors, torch .bin, or npz).
    Raises KeyError with the missing HF name when the checkpoint does
    not cover the config's shape."""
    pre = _strip_prefix(list(tensors))

    def t(name: str) -> np.ndarray:
        full = pre + name
        if full not in tensors:
            raise KeyError(f"checkpoint missing tensor {full!r}")
        return np.asarray(tensors[full], np.float32)

    def dense(hf: str) -> Dict[str, np.ndarray]:
        # torch Linear stores [out, in]; flax Dense kernels are [in, out]
        return {"kernel": t(hf + ".weight").T, "bias": t(hf + ".bias")}

    def ln(hf: str) -> Dict[str, np.ndarray]:
        return {"scale": t(hf + ".weight"), "bias": t(hf + ".bias")}

    params: Dict[str, Any] = {
        "tok_embed": {"embedding": t("embeddings.word_embeddings.weight")},
        "pos_embed": {
            "embedding": t("embeddings.position_embeddings.weight")},
        "type_embed": {
            "embedding": (
                t("embeddings.token_type_embeddings.weight")
                if pre + "embeddings.token_type_embeddings.weight" in tensors
                else np.zeros((max(cfg.type_vocab_size, 1), cfg.hidden_size),
                              np.float32))},
        "emb_ln": ln("embeddings.LayerNorm"),
    }
    for i in range(cfg.num_layers):
        hf = f"encoder.layer.{i}"
        params[f"layer_{i}_q"] = dense(f"{hf}.attention.self.query")
        params[f"layer_{i}_k"] = dense(f"{hf}.attention.self.key")
        params[f"layer_{i}_v"] = dense(f"{hf}.attention.self.value")
        params[f"layer_{i}_o"] = dense(f"{hf}.attention.output.dense")
        params[f"layer_{i}_attn_ln"] = ln(f"{hf}.attention.output.LayerNorm")
        params[f"layer_{i}_mlp_up"] = dense(f"{hf}.intermediate.dense")
        params[f"layer_{i}_mlp_down"] = dense(f"{hf}.output.dense")
        params[f"layer_{i}_mlp_ln"] = ln(f"{hf}.output.LayerNorm")
    return params


def read_checkpoint_tensors(path: str) -> Dict[str, np.ndarray]:
    """Load name->array from .safetensors, torch .bin/.pt, or .npz."""
    if path.endswith(".safetensors"):
        from safetensors.numpy import load_file

        return dict(load_file(path))
    if path.endswith(".npz"):
        return {k: v for k, v in np.load(path).items()}
    # torch pickle (weights_only=True: no arbitrary code execution)
    import torch

    sd = torch.load(path, map_location="cpu", weights_only=True)
    if hasattr(sd, "state_dict"):
        sd = sd.state_dict()
    return {k: v.detach().cpu().numpy() for k, v in sd.items()}


_WEIGHT_FILES = (
    "model.safetensors", "pytorch_model.bin", "model.npz",
)


def load_hf_model_dir(model_dir: str, pooling: str = "mean"):
    """(cfg, params) from a local HF model directory."""
    with open(os.path.join(model_dir, "config.json"), encoding="utf-8") as f:
        cfg = HFEncoderConfig.from_hf_config(json.load(f))
    if pooling != cfg.pooling:
        import dataclasses

        cfg = dataclasses.replace(cfg, pooling=pooling)
    for fname in _WEIGHT_FILES:
        path = os.path.join(model_dir, fname)
        if os.path.exists(path):
            tensors = read_checkpoint_tensors(path)
            return cfg, import_hf_params(tensors, cfg)
    raise FileNotFoundError(
        f"no weight file in {model_dir!r} (looked for {_WEIGHT_FILES})")


class HFEncoderEmbedder:
    """embed/embed_batch over an imported HF encoder, using the model's
    own tokenizer (AutoTokenizer over LOCAL files only — never
    downloads). Drop-in for the Embedder protocol (embed/embedder.py)."""

    def __init__(self, model_dir: str, pooling: str = "mean",
                 max_batch: int = 16, max_len: int = 512):
        import threading

        cfg, params = load_hf_model_dir(model_dir, pooling=pooling)
        self.cfg = cfg
        self.params = params
        self.model = HFEncoder(cfg)
        self.dims = cfg.hidden_size
        self.max_batch = max_batch
        self.max_len = min(max_len, cfg.max_position_embeddings - 2)
        from transformers import AutoTokenizer

        self.tokenizer = AutoTokenizer.from_pretrained(
            model_dir, local_files_only=True)
        self._jit = jax.jit(
            lambda p, ids, m: self.model.apply({"params": p}, ids, m))
        self._lock = threading.Lock()

    def embed_batch(self, texts: Sequence[str]) -> List[List[float]]:
        out: List[List[float]] = []
        for start in range(0, len(texts), self.max_batch):
            chunk = list(texts[start:start + self.max_batch])
            enc = self.tokenizer(
                chunk, padding=True, truncation=True,
                max_length=self.max_len, return_tensors="np")
            ids = enc["input_ids"].astype(np.int32)
            mask = enc["attention_mask"].astype(bool)
            with self._lock:
                vecs = self._jit(self.params, jnp.asarray(ids),
                                 jnp.asarray(mask))
            out.extend(np.asarray(vecs, np.float32).tolist())
        return out

    def embed(self, text: str) -> List[float]:
        return self.embed_batch([text])[0]


def default_model_dir() -> Optional[str]:
    """NORNICDB_TPU_MODEL_DIR when it points at a loadable model dir."""
    d = os.environ.get("NORNICDB_TPU_MODEL_DIR", "")
    if d and os.path.exists(os.path.join(d, "config.json")):
        return d
    return None
