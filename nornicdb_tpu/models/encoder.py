"""Transformer text encoder (bge-m3-style) in flax.

The reference embeds with bge-m3 (an XLM-RoBERTa-large derivative) through
llama.cpp (pkg/embed/local_gguf.go:57 LocalGGUFEmbedder). Here the encoder
is a native JAX/flax module designed for TPU:

- bfloat16 activations, f32 params/normalization — MXU-friendly;
- every activation carries a logical sharding annotation so the same
  module runs single-chip or pjit-sharded over a (dp, tp, sp) mesh with
  XLA inserting the collectives (scaling-book recipe);
- mean pooling + L2 norm = drop-in embedding vectors for the search
  stack.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.lax import with_sharding_constraint as _wsc
from jax.sharding import PartitionSpec as P


@dataclass(frozen=True)
class EncoderConfig:
    vocab_size: int = 30522
    hidden_size: int = 384
    num_layers: int = 6
    num_heads: int = 6
    mlp_dim: int = 1536
    max_len: int = 512
    dropout: float = 0.0
    dtype: Any = jnp.bfloat16
    # logical mesh axes ('' disables the constraint when no mesh is active)
    shard_activations: bool = False
    # when a mesh with sp > 1 is attached, attention routes through ring
    # attention (sequence-sharded, no [S, S] materialization)
    mesh: Any = None
    # single-chip fused Pallas attention; resolved at CONSTRUCTION by the
    # inference stack (never set for training: the kernel has no vjp, and
    # never combined with a multi-device mesh: pallas_call has no GSPMD
    # partitioning rule)
    use_flash_attention: bool = False

    @staticmethod
    def tiny() -> "EncoderConfig":
        return EncoderConfig(vocab_size=1024, hidden_size=64, num_layers=2,
                             num_heads=4, mlp_dim=128, max_len=128)

    @staticmethod
    def mini() -> "EncoderConfig":
        """The committed-checkpoint shape (models/pretrain.py): big
        enough to learn topic-level co-occurrence structure (8k hash
        vocab keeps collisions from blurring topical terms), small
        enough that the fp16 checkpoint stays a few MB in git."""
        return EncoderConfig(vocab_size=8192, hidden_size=160,
                             num_layers=2, num_heads=4, mlp_dim=640,
                             max_len=512, dtype=jnp.float32)

    @staticmethod
    def bge_m3_like() -> "EncoderConfig":
        """XLM-R-large shape (bge-m3's backbone)."""
        return EncoderConfig(vocab_size=250_002, hidden_size=1024,
                             num_layers=24, num_heads=16, mlp_dim=4096,
                             max_len=8192)


def _maybe_shard(x: jnp.ndarray, cfg: EncoderConfig, spec: P) -> jnp.ndarray:
    """Annotate activation sharding; under plain jit (no mesh) this is a
    no-op, under pjit it pins [batch->dp, seq->sp, hidden->tp]."""
    if not cfg.shard_activations:
        return x
    try:
        return _wsc(x, spec)
    except RuntimeError as exc:
        # tolerate ONLY the no-mesh case (single-device run of a shardable
        # config); genuine sharding errors must fail loudly
        if "non-empty mesh" in str(exc):
            return x
        raise


def flash_attention_enabled() -> bool:
    """Opt-in fused Pallas attention (NORNICDB_PALLAS_ATTENTION=1). Off
    by default for the same reason as the top-k kernel: interpret mode
    is test-only and real-TPU validation gates enabling it broadly.
    Consumed at encoder CONSTRUCTION by the inference embedder; the
    training path never opts in (the kernel has no vjp)."""
    import os

    return os.environ.get("NORNICDB_PALLAS_ATTENTION", "0") == "1"


class MultiHeadAttention(nn.Module):
    cfg: EncoderConfig

    @nn.compact
    def __call__(self, x: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
        cfg = self.cfg
        d = cfg.hidden_size
        h = cfg.num_heads
        head_dim = d // h
        # qkv projections: kernel sharded over tp on the head axis
        dense = lambda name: nn.DenseGeneral(  # noqa: E731
            features=(h, head_dim), axis=-1, dtype=cfg.dtype, name=name,
        )
        q = dense("query")(x)  # [B, S, h, hd]
        k = dense("key")(x)
        v = dense("value")(x)
        q = _maybe_shard(q, cfg, P("dp", "sp", "tp", None))
        if cfg.mesh is not None and cfg.mesh.shape.get("sp", 1) > 1:
            # sequence-parallel path: exact ring attention over the sp axis
            # (K/V blocks rotate via ppermute; no [S, S] materialization)
            from nornicdb_tpu.parallel.ring_attention import ring_attention

            out = ring_attention(
                q, k, v, mask, mesh=cfg.mesh,
                axis_name="sp", batch_axis="dp", head_axis="tp",
            )
        elif cfg.use_flash_attention and cfg.mesh is None:
            # fused Pallas path: blockwise online-softmax attention, no
            # [S, S] HBM matrix (ops/pallas_attention.py). Construction-
            # time opt-in for single-chip inference only — no vjp, and
            # no GSPMD partitioning rule for the custom call.
            from nornicdb_tpu.ops.pallas_attention import flash_attention

            out = flash_attention(q, k, v, mask)
        else:
            k = _maybe_shard(k, cfg, P("dp", None, "tp", None))
            v = _maybe_shard(v, cfg, P("dp", None, "tp", None))
            scale = head_dim ** -0.5
            # [B, h, S, S] — XLA fuses the softmax chain
            logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
            big_neg = jnp.finfo(cfg.dtype).min
            logits = jnp.where(mask[:, None, None, :], logits, big_neg)
            weights = jax.nn.softmax(
                logits.astype(jnp.float32), axis=-1
            ).astype(cfg.dtype)
            out = jnp.einsum("bhqk,bkhd->bqhd", weights, v)
        out = _maybe_shard(out, cfg, P("dp", "sp", "tp", None))
        return nn.DenseGeneral(
            features=d, axis=(-2, -1), dtype=cfg.dtype, name="out"
        )(out)


class TransformerLayer(nn.Module):
    cfg: EncoderConfig

    @nn.compact
    def __call__(self, x: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
        cfg = self.cfg
        y = nn.LayerNorm(dtype=jnp.float32, name="ln1")(x)
        y = MultiHeadAttention(cfg, name="attn")(y, mask)
        x = x + y
        y = nn.LayerNorm(dtype=jnp.float32, name="ln2")(x)
        y = nn.Dense(cfg.mlp_dim, dtype=cfg.dtype, name="mlp_up")(y)
        y = _maybe_shard(y, cfg, P("dp", "sp", "tp"))
        y = nn.gelu(y)
        y = nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="mlp_down")(y)
        x = x + y
        return _maybe_shard(x, cfg, P("dp", "sp", None))


class Encoder(nn.Module):
    """Token ids -> L2-normalized sentence embedding."""

    cfg: EncoderConfig

    @nn.compact
    def __call__(
        self, token_ids: jnp.ndarray, attention_mask: Optional[jnp.ndarray] = None
    ) -> jnp.ndarray:
        cfg = self.cfg
        if attention_mask is None:
            attention_mask = (token_ids != 0)
        x = nn.Embed(
            cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype, name="tok_embed"
        )(token_ids)
        pos = jnp.arange(token_ids.shape[1])[None, :]
        x = x + nn.Embed(
            cfg.max_len, cfg.hidden_size, dtype=cfg.dtype, name="pos_embed"
        )(pos)
        x = _maybe_shard(x, cfg, P("dp", "sp", None))
        for i in range(cfg.num_layers):
            x = TransformerLayer(cfg, name=f"layer_{i}")(x, attention_mask)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_final")(x)
        # masked mean pooling
        m = attention_mask[:, :, None].astype(jnp.float32)
        pooled = jnp.sum(x.astype(jnp.float32) * m, axis=1) / jnp.maximum(
            jnp.sum(m, axis=1), 1.0
        )
        from nornicdb_tpu.ops.similarity import l2_normalize

        return l2_normalize(pooled)


def param_sharding_rules(cfg: EncoderConfig):
    """Logical->mesh partitioning for pjit: attention heads and MLP width
    over ``tp``, embeddings over ``tp`` on the hidden axis, everything else
    replicated. Applied by models.train.make_sharded_train_step."""

    def rule(path: str, value) -> P:
        if value.ndim == 1:
            return P()
        if "tok_embed" in path or "pos_embed" in path:
            return P(None, "tp")
        if "attn" in path and ("query" in path or "key" in path or "value" in path):
            if value.ndim == 3:
                return P(None, "tp", None)  # kernel [d, h, hd] — heads over tp
            return P("tp", None)  # bias [h, hd]
        if "attn" in path and "out" in path:
            if value.ndim == 3:
                return P("tp", None, None)  # kernel [h, hd, d]
            return P()
        if "mlp_up" in path and value.ndim == 2:
            return P(None, "tp")  # [d, 4d]
        if "mlp_down" in path and value.ndim == 2:
            return P("tp", None)  # [4d, d]
        return P(*([None] * value.ndim))

    return rule
