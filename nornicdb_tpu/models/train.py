"""Contrastive training step for the encoder, sharded over a device mesh.

The reference never trains (inference-only llama.cpp); training support is
what makes the TPU embedding stack self-improving (fine-tune bge-m3-style
encoders on the graph's own co-access/link data). The step is the standard
InfoNCE in-batch-negatives objective.

Sharding design (scaling-book recipe): pick a mesh (dp, tp, sp), annotate
param shardings (encoder.param_sharding_rules) and batch shardings
(batch -> dp, sequence -> sp), jit, and let XLA insert the collectives:
- dp: gradients all-reduce over ICI,
- tp: attention-head/MLP-width partials reduce-scatter inside each layer,
- sp: sequence-sharded activations; attention gathers K/V over sp.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import struct
from flax.core import FrozenDict
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from nornicdb_tpu.models.encoder import Encoder, EncoderConfig, param_sharding_rules


class TrainState(struct.PyTreeNode):
    step: jnp.ndarray
    params: Any
    opt_state: Any
    tx: optax.GradientTransformation = struct.field(pytree_node=False)

    def apply_gradients(self, grads):
        updates, new_opt = self.tx.update(grads, self.opt_state, self.params)
        return self.replace(
            step=self.step + 1,
            params=optax.apply_updates(self.params, updates),
            opt_state=new_opt,
        )


def create_train_state(
    cfg: EncoderConfig,
    rng: jax.Array,
    learning_rate: float = 1e-4,
    seq_len: int = 64,
) -> Tuple[Encoder, TrainState]:
    model = Encoder(cfg)
    dummy = jnp.ones((2, seq_len), jnp.int32)
    params = model.init(rng, dummy)["params"]
    tx = optax.adamw(learning_rate)
    state = TrainState(
        step=jnp.zeros((), jnp.int32), params=params,
        opt_state=tx.init(params), tx=tx,
    )
    return model, state


def info_nce_loss(
    anchors: jnp.ndarray, positives: jnp.ndarray, temperature: float = 0.05
) -> jnp.ndarray:
    """Symmetric in-batch negatives: row i's positive is column i, and
    the loss runs both directions (anchor->positive and
    positive->anchor) — the asymmetric query/document window pairs mean
    each direction carries distinct gradient signal."""
    logits = anchors @ positives.T / temperature  # [B, B]
    labels = jnp.arange(logits.shape[0])
    return 0.5 * (
        jnp.mean(optax.softmax_cross_entropy_with_integer_labels(
            logits, labels))
        + jnp.mean(optax.softmax_cross_entropy_with_integer_labels(
            logits.T, labels))
    )


def contrastive_train_step(
    model: Encoder,
    state: TrainState,
    anchor_ids: jnp.ndarray,
    positive_ids: jnp.ndarray,
) -> Tuple[TrainState, jnp.ndarray]:
    """One unsharded (single-device) step; jit-cache with
    jax.jit(functools.partial(contrastive_train_step, model))."""

    def loss_fn(params):
        a = model.apply({"params": params}, anchor_ids)
        p = model.apply({"params": params}, positive_ids)
        return info_nce_loss(a, p)

    loss, grads = jax.value_and_grad(loss_fn)(state.params)
    return state.apply_gradients(grads), loss


def _param_shardings(params, cfg: EncoderConfig, mesh: Mesh):
    rule = param_sharding_rules(cfg)

    def assign(path, value):
        path_str = "/".join(str(k.key) for k in path)
        return NamedSharding(mesh, rule(path_str, value))

    return jax.tree_util.tree_map_with_path(assign, params)


def make_sharded_train_step(
    model: Encoder,
    state: TrainState,
    mesh: Mesh,
) -> Tuple[TrainState, Callable]:
    """Place ``state`` onto the mesh per the partitioning rules and return
    (sharded_state, jitted_step). The step shards batch over dp and
    sequence over sp; XLA inserts all collectives (GSPMD)."""
    import dataclasses

    cfg = model.cfg
    if cfg.mesh is not mesh:
        # attach the mesh so attention takes the ring (sp) path
        model = Encoder(dataclasses.replace(cfg, mesh=mesh))
    param_sh = _param_shardings(state.params, cfg, mesh)
    opt_sh = _opt_shardings(state, param_sh, mesh)
    state = state.replace(
        params=jax.device_put(state.params, param_sh),
        opt_state=jax.device_put(state.opt_state, opt_sh),
        step=jax.device_put(state.step, NamedSharding(mesh, P())),
    )
    data_sh = NamedSharding(mesh, P("dp", "sp"))

    def step_fn(st: TrainState, anchor_ids, positive_ids):
        return contrastive_train_step(model, st, anchor_ids, positive_ids)

    state_sh = TrainState(
        step=NamedSharding(mesh, P()),
        params=param_sh,
        opt_state=opt_sh,
        tx=state.tx,
    )
    jitted = jax.jit(
        step_fn,
        in_shardings=(state_sh, data_sh, data_sh),
        out_shardings=(state_sh, NamedSharding(mesh, P())),
    )

    def run(st, anchor_ids, positive_ids):
        # activation sharding constraints use raw PartitionSpecs, which
        # need the mesh in context at trace time
        from nornicdb_tpu.parallel.mesh import mesh_context

        with mesh_context(mesh):
            return jitted(st, anchor_ids, positive_ids)

    return state, run


def _opt_shardings(state: TrainState, param_sh, mesh: Mesh):
    """adamw state = (ScaleByAdamState(count, mu, nu), extras): moments get
    the param shardings, scalars replicate."""

    def assign(x):
        return NamedSharding(mesh, P())

    def walk(opt_state):
        out = []
        for part in opt_state:
            if hasattr(part, "mu") and hasattr(part, "nu"):
                out.append(
                    part._replace(
                        count=NamedSharding(mesh, P()),
                        mu=param_sh,
                        nu=param_sh,
                    )
                )
            else:
                out.append(jax.tree_util.tree_map(assign, part))
        return tuple(out)

    return walk(state.opt_state)
