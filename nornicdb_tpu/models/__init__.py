"""Model layer: the TPU-native embedding/inference stack.

Replaces the reference's llama.cpp path (pkg/localllm, vendored GGUF
inference with CUDA/Metal offload — llama.go:35-56) and its bge-m3
embedding pipeline (pkg/embed/local_gguf.go) with a flax encoder served
via jit/pjit over a device mesh.
"""

from nornicdb_tpu.models.encoder import Encoder, EncoderConfig  # noqa: F401
from nornicdb_tpu.models.train import (  # noqa: F401
    TrainState,
    contrastive_train_step,
    create_train_state,
    make_sharded_train_step,
)
