"""Contrastive pretraining for the committed mini encoder checkpoint.

The reference ships real bge-m3 weights for local embedding
(pkg/embed/local_gguf.go:57,100 over vendored llama.cpp). This image has
no network, so the equivalent here is a small encoder trained IN-REPO on
locally-available English prose — Python standard-library module
docstrings plus this repo's own documentation — with an InfoNCE
objective (models/train.py): two word-windows of the same document are
positives, in-batch others are negatives. The result learns topical
co-occurrence structure on top of the hash tokenizer, which is what
separates it from the bag-of-hashes HashEmbedder baseline: windows that
share a topic but not exact words still land near each other.

The trained checkpoint is committed (models/checkpoints/encoder_mini.npz,
fp16, ~1.5 MB) and is the DB's default embedder (db.py); quality is
gated in CI by tests/test_encoder_eval.py over a committed JSONL suite.

CLI: python -m nornicdb_tpu.models.pretrain [out.npz] [steps]
"""

from __future__ import annotations

import io
import os
import random
import re
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

# modules whose docstrings form the training corpus: stable, offline,
# real English across distinct technical topics
_CORPUS_MODULES = [
    "abc", "argparse", "array", "asyncio", "base64", "bisect", "calendar",
    "cmath", "codecs", "collections", "colorsys", "concurrent.futures",
    "configparser", "contextlib", "copy", "csv", "ctypes", "datetime",
    "decimal", "difflib", "dis", "doctest", "email", "enum", "fileinput",
    "fnmatch", "fractions", "functools", "getpass", "gettext", "glob",
    "gzip", "hashlib", "heapq", "hmac", "html", "http", "imaplib",
    "importlib", "inspect", "io", "ipaddress", "itertools", "json",
    "keyword", "linecache", "locale", "logging", "lzma", "mailbox",
    "math", "mimetypes", "multiprocessing", "netrc", "numbers",
    "operator", "os", "pathlib", "pdb", "pickle", "pickletools",
    "platform", "plistlib", "poplib", "pprint", "profile", "pstats",
    "py_compile", "queue", "quopri", "random", "re", "reprlib",
    "sched", "secrets", "selectors", "shelve", "shlex", "shutil",
    "signal", "smtplib", "socket", "socketserver", "sqlite3", "ssl",
    "stat", "statistics", "string", "stringprep", "struct", "subprocess",
    "symtable", "sysconfig", "tabnanny", "tarfile", "tempfile",
    "textwrap", "threading", "timeit", "token", "tokenize", "trace",
    "traceback", "types", "typing", "unicodedata", "unittest", "urllib",
    "uuid", "venv", "warnings", "wave", "weakref", "webbrowser",
    "xml", "zipapp", "zipfile", "zlib",
]


def build_corpus(min_words: int = 12) -> List[Tuple[str, str]]:
    """(group, text) documents: stdlib module + member (class/function)
    docstrings + repo doc sections.

    The GROUP is the retrieval-relevant unit: all docstrings of one
    stdlib module are about one topic, exactly the granularity search
    eval groups documents at. Contrastive pairs drawn from two DIFFERENT
    documents of the same group (make_batch) teach topic-level
    clustering — same-document windows alone only teach document
    identity, which is why the round-3 recipe's recall plateaued at the
    lexical baseline. Repo doc sections cover many topics per file, so
    each section is its own group (same-doc windows)."""
    docs: List[Tuple[str, str]] = []
    seen = set()

    def take(group: str, text: Optional[str]) -> None:
        text = (text or "").strip()
        if len(text.split()) >= min_words and text[:80] not in seen:
            seen.add(text[:80])
            docs.append((group, text))

    for name in _CORPUS_MODULES:
        try:
            import importlib

            mod = importlib.import_module(name)
        except Exception:
            continue
        group = name.split(".")[0]
        take(group, mod.__doc__)
        for member in vars(mod).values():
            try:
                take(group, getattr(member, "__doc__", None))
                if isinstance(member, type):
                    for sub in vars(member).values():
                        take(group, getattr(sub, "__doc__", None))
            except Exception:
                continue
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    for fname in ("README.md", "SURVEY.md", "COMPONENTS.md"):
        path = os.path.join(repo, fname)
        if os.path.exists(path):
            with io.open(path, encoding="utf-8") as f:
                text = f.read()
            # split large docs into section-sized documents
            for si, part in enumerate(re.split(r"\n#+ ", text)):
                if len(part.split()) >= 25:
                    docs.append((f"{fname}#{si}", part))
    return docs


def _window(words: List[str], rng: random.Random,
            lo: int, hi: int, drop: float) -> str:
    n = len(words)
    w = rng.randint(lo, hi)
    start = rng.randint(0, max(0, n - w))
    win = [t for t in words[start: start + w] if rng.random() > drop]
    return " ".join(win) if win else words[start]


def make_batch(
    groups: Dict[str, List[List[str]]],
    group_names: List[str],
    tokenizer,
    rng: random.Random,
    batch: int,
    seq_len: int,
) -> Tuple[np.ndarray, np.ndarray]:
    """One (anchor, positive) pair per DISTINCT group.

    - anchor: short (4-14 word) heavy-dropout window — query-shaped;
    - positive: longer window from a DIFFERENT document of the same
      group when the group has several (topic-level positive), else
      from the same document (identity-level fallback);
    - one pair per group per batch, so in-batch negatives are never
      secretly same-topic (same-group negatives would push the very
      structure we want apart)."""
    picks = rng.sample(group_names, min(batch, len(group_names)))
    a = np.zeros((len(picks), seq_len), np.int32)
    p = np.zeros((len(picks), seq_len), np.int32)
    for row, g in enumerate(picks):
        members = groups[g]
        d1 = rng.randrange(len(members))
        if len(members) > 1:  # topic-level positive: a DIFFERENT doc
            d2 = rng.randrange(len(members) - 1)
            if d2 >= d1:
                d2 += 1
        else:
            d2 = d1  # singleton group: identity-level fallback
        wa = _window(members[d1], rng, 4, 14, drop=0.3)
        wp = _window(members[d2], rng, 16, 48, drop=0.1)
        for arr, text in ((a, wa), (p, wp)):
            ids = tokenizer.encode(text, max_len=seq_len)
            arr[row, : len(ids)] = ids
    return a, p


def train_mini(
    steps: int = 3000,
    batch: int = 128,
    seq_len: int = 64,
    learning_rate: float = 3e-4,
    seed: int = 0,
    log_every: int = 200,
    eval_hook=None,
):
    """Train the mini encoder; returns (cfg, params, final_loss).

    ``eval_hook(step, params)`` (optional) is called every ``log_every``
    steps for in-training quality probes."""
    import functools

    import jax
    import optax

    from nornicdb_tpu.embed.tokenizer import HashTokenizer
    from nornicdb_tpu.models.encoder import EncoderConfig
    from nornicdb_tpu.models.train import (
        contrastive_train_step,
        create_train_state,
    )

    cfg = EncoderConfig.mini()
    tokenizer = HashTokenizer(cfg.vocab_size)
    groups: Dict[str, List[List[str]]] = {}
    for g, text in build_corpus():
        groups.setdefault(g, []).append(text.split())
    group_names = sorted(groups)
    batch = min(batch, len(group_names))
    rng = random.Random(seed)
    schedule = optax.warmup_cosine_decay_schedule(
        init_value=0.0, peak_value=learning_rate,
        warmup_steps=min(100, steps // 10), decay_steps=steps,
        end_value=learning_rate * 0.03,
    )
    model, state = create_train_state(
        cfg, jax.random.PRNGKey(seed), learning_rate=schedule,
        seq_len=seq_len,
    )
    step_fn = jax.jit(functools.partial(contrastive_train_step, model))
    loss = float("nan")
    for step in range(steps):
        a, p = make_batch(groups, group_names, tokenizer, rng, batch,
                          seq_len)
        state, loss_arr = step_fn(state, a, p)
        if log_every and (step + 1) % log_every == 0:
            loss = float(loss_arr)
            print(f"step {step + 1}/{steps} loss {loss:.4f}", flush=True)
            if eval_hook is not None:
                eval_hook(step + 1, state.params)
    return cfg, state.params, float(loss_arr)


# -- checkpoint io ---------------------------------------------------------


def save_checkpoint(path: str, cfg, params) -> None:
    """fp16 flax-serialized params + the config fields that shape them."""
    import jax
    from flax import serialization

    half = jax.tree_util.tree_map(
        lambda x: np.asarray(x, np.float16), params
    )
    blob = serialization.to_bytes(half)
    np.savez_compressed(
        path,
        params=np.frombuffer(blob, dtype=np.uint8),
        meta=np.asarray([
            cfg.vocab_size, cfg.hidden_size, cfg.num_layers,
            cfg.num_heads, cfg.mlp_dim, cfg.max_len,
        ], dtype=np.int64),
    )


def load_checkpoint(path: str):
    """Returns (cfg, params) with fp32 params."""
    import jax
    from flax import serialization

    from nornicdb_tpu.models.encoder import Encoder, EncoderConfig

    data = np.load(path if path.endswith(".npz") else path + ".npz")
    meta = [int(x) for x in data["meta"]]
    cfg = EncoderConfig(
        vocab_size=meta[0], hidden_size=meta[1], num_layers=meta[2],
        num_heads=meta[3], mlp_dim=meta[4], max_len=meta[5],
    )
    model = Encoder(cfg)
    template = model.init(
        jax.random.PRNGKey(0), np.ones((1, 8), np.int32)
    )["params"]
    half_template = jax.tree_util.tree_map(
        lambda x: np.asarray(x, np.float16), template
    )
    params = serialization.from_bytes(
        half_template, data["params"].tobytes()
    )
    params = jax.tree_util.tree_map(
        lambda x: np.asarray(x, np.float32), params
    )
    return cfg, params


def default_checkpoint_path() -> Optional[str]:
    """Path of the committed mini checkpoint, or None if absent."""
    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "checkpoints", "encoder_mini.npz",
    )
    return path if os.path.exists(path) else None


def load_default_embedder():
    """The DB's default semantic embedder: the committed mini encoder
    behind the batched jax embedder; None when no checkpoint is
    committed (callers fall back to HashEmbedder)."""
    path = default_checkpoint_path()
    if path is None:
        return None
    from nornicdb_tpu.embed.embedder import JaxEncoderEmbedder
    from nornicdb_tpu.models.encoder import Encoder

    cfg, params = load_checkpoint(path)
    return JaxEncoderEmbedder(model=Encoder(cfg), params=params, cfg=cfg)


def main() -> None:  # pragma: no cover
    import sys

    # CPU always: pretraining is tiny, and the container's sitecustomize
    # pins jax_platforms="axon,cpu" whose TPU init can hang for minutes
    # when the tunnel is down (the env var alone is not enough)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

    out = sys.argv[1] if len(sys.argv) > 1 else os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "checkpoints", "encoder_mini.npz",
    )
    steps = int(sys.argv[2]) if len(sys.argv) > 2 else 400
    os.makedirs(os.path.dirname(out), exist_ok=True)
    cfg, params, loss = train_mini(steps=steps)
    save_checkpoint(out, cfg, params)
    size = os.path.getsize(out) / 1e6
    print(f"saved {out} ({size:.2f} MB, final loss {loss:.4f})")


if __name__ == "__main__":  # pragma: no cover
    main()
