"""Kalman filters used by decay/temporal/inference smoothing.

Reference: pkg/filter — kalman.go (basic), kalman_adaptive.go,
kalman_velocity.go (1,561 LoC). Scalar filters; the math is identical, in
a fraction of the code.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class KalmanFilter:
    """1-D constant-state Kalman filter."""

    process_noise: float = 1e-3  # Q
    measurement_noise: float = 1e-1  # R
    estimate: float = 0.0
    error: float = 1.0  # P
    initialized: bool = False

    def update(self, measurement: float) -> float:
        if not self.initialized:
            self.estimate = measurement
            self.initialized = True
            return self.estimate
        # predict
        self.error += self.process_noise
        # update
        gain = self.error / (self.error + self.measurement_noise)
        self.estimate += gain * (measurement - self.estimate)
        self.error *= 1.0 - gain
        return self.estimate


@dataclass
class AdaptiveKalmanFilter(KalmanFilter):
    """Adapts measurement noise to the innovation magnitude
    (reference: kalman_adaptive.go)."""

    adapt_rate: float = 0.05

    def update(self, measurement: float) -> float:
        if self.initialized:
            innovation = abs(measurement - self.estimate)
            self.measurement_noise = (
                (1.0 - self.adapt_rate) * self.measurement_noise
                + self.adapt_rate * innovation * innovation
            )
            self.measurement_noise = max(self.measurement_noise, 1e-6)
        return super().update(measurement)


class VelocityKalmanFilter:
    """2-state (position, velocity) filter for access-rate trends
    (reference: kalman_velocity.go)."""

    def __init__(self, process_noise: float = 1e-3, measurement_noise: float = 1e-1):
        self.q = process_noise
        self.r = measurement_noise
        self.pos = 0.0
        self.vel = 0.0
        # covariance
        self.p00, self.p01, self.p10, self.p11 = 1.0, 0.0, 0.0, 1.0
        self.initialized = False
        self._last_t: float | None = None

    def update(self, measurement: float, t: float) -> tuple[float, float]:
        if not self.initialized:
            self.pos = measurement
            self.initialized = True
            self._last_t = t
            return self.pos, self.vel
        last = self._last_t if self._last_t is not None else t
        dt = max(t - last, 1e-9)
        self._last_t = t
        # predict
        self.pos += self.vel * dt
        self.p00 += dt * (self.p10 + self.p01 + dt * self.p11) + self.q
        self.p01 += dt * self.p11
        self.p10 += dt * self.p11
        self.p11 += self.q
        # update position measurement — the covariance update must use the
        # PRIOR (predicted) values throughout, or the gain stays inflated
        innovation = measurement - self.pos
        s = self.p00 + self.r
        k0 = self.p00 / s
        k1 = self.p10 / s
        self.pos += k0 * innovation
        self.vel += k1 * innovation
        p00, p01, p10, p11 = self.p00, self.p01, self.p10, self.p11
        self.p00 = (1 - k0) * p00
        self.p01 = (1 - k0) * p01
        self.p10 = p10 - k1 * p00
        self.p11 = p11 - k1 * p01
        return self.pos, self.vel
