"""Generator backends for Heimdall.

Reference: pkg/heimdall generator backends — local GGUF (cgo llama.cpp),
OpenAI, Ollama, yzma (types.go, scheduler.go). Here: JAXGenerator (the
TPU-native in-process SLM), OpenAI/Ollama HTTP backends, and a
deterministic EchoGenerator test double (the universal fixture, as the
reference's tests use stub generators).
"""

from __future__ import annotations

import json
import urllib.request
from typing import Dict, Iterator, List, Optional, Protocol


Message = Dict[str, str]  # {"role": ..., "content": ...}


def render_chat(messages: List[Message]) -> str:
    """Flatten a chat transcript into a single prompt."""
    parts = [f"{m.get('role', 'user')}: {m.get('content', '')}"
             for m in messages]
    parts.append("assistant:")
    return "\n".join(parts)


class Generator(Protocol):
    name: str

    def generate(self, prompt: str, max_tokens: int = 256,
                 temperature: float = 0.0) -> str: ...

    def generate_stream(self, prompt: str, max_tokens: int = 256,
                        temperature: float = 0.0) -> Iterator[str]: ...


class EchoGenerator:
    """Deterministic test double; optionally scripted replies."""

    def __init__(self, name: str = "echo",
                 replies: Optional[List[str]] = None):
        self.name = name
        self._replies = list(replies or [])
        self.calls: List[str] = []

    def generate(self, prompt: str, max_tokens: int = 256,
                 temperature: float = 0.0) -> str:
        self.calls.append(prompt)
        if self._replies:
            return self._replies.pop(0)
        return f"echo: {prompt[-200:]}"

    def generate_stream(self, prompt: str, max_tokens: int = 256,
                        temperature: float = 0.0) -> Iterator[str]:
        text = self.generate(prompt, max_tokens, temperature)
        for i in range(0, len(text), 8):
            yield text[i:i + 8]


class JAXGenerator:
    """In-process TPU SLM (reference analog: local GGUF llama.cpp
    backend). Weights resolve in order: explicit params > checkpoint
    path > an imported LLaMA-class model (NORNICDB_TPU_SLM_DIR,
    heimdall/hf_import.py) > the committed tiny checkpoint (trained
    in-repo, heimdall/train.py) > random init as a last resort."""

    def __init__(self, name: str = "heimdall-slm", cfg=None, params=None,
                 checkpoint: Optional[str] = None):
        from nornicdb_tpu.heimdall.model import DecoderModel

        self.name = name
        if params is None and cfg is None and checkpoint is None:
            from nornicdb_tpu.heimdall.hf_import import default_slm_dir

            slm_dir = default_slm_dir()
            if slm_dir is not None:
                from nornicdb_tpu.heimdall.hf_import import HFDecoderModel

                self.model = HFDecoderModel(slm_dir)
                return
        if params is None:
            from nornicdb_tpu.heimdall.train import (
                default_checkpoint_path,
                load_params,
            )

            # the committed default only applies when the caller didn't
            # pin an architecture — a supplied cfg means "that model",
            # not "whatever the tiny checkpoint happens to be"
            path = checkpoint or (
                default_checkpoint_path() if cfg is None else None
            )
            if path is not None:
                try:
                    cfg, params = load_params(path)
                except (OSError, KeyError, ValueError):
                    if checkpoint is not None:
                        raise  # explicit checkpoint must not fail silently
        self.model = DecoderModel(cfg=cfg, params=params)

    def param_bytes(self) -> int:
        return self.model.param_bytes()

    def generate(self, prompt: str, max_tokens: int = 256,
                 temperature: float = 0.0) -> str:
        return self.model.generate(prompt, max_tokens=max_tokens,
                                   temperature=temperature)

    def generate_stream(self, prompt: str, max_tokens: int = 256,
                        temperature: float = 0.0) -> Iterator[str]:
        # decode is a single fused device loop; stream in host chunks
        text = self.generate(prompt, max_tokens, temperature)
        for i in range(0, len(text), 16):
            yield text[i:i + 16]


class _HttpGenerator:
    timeout = 60.0
    retries = 1

    def _post(self, url: str, payload: dict, headers: dict) -> dict:
        # shared retrying POST (embed/http_providers.py) — one HTTP
        # helper for both the embedding and generation backends
        from nornicdb_tpu.embed.http_providers import _post_json

        return _post_json(url, payload, headers=headers,
                          timeout=self.timeout, retries=self.retries)


class OpenAIGenerator(_HttpGenerator):
    """OpenAI-compatible HTTP backend (reference: OpenAI generator)."""

    def __init__(self, base_url: str, model: str, api_key: str = "",
                 name: Optional[str] = None):
        self.base_url = base_url.rstrip("/")
        self.model = model
        self.api_key = api_key
        self.name = name or f"openai:{model}"

    def generate(self, prompt: str, max_tokens: int = 256,
                 temperature: float = 0.0) -> str:
        headers = (
            {"Authorization": f"Bearer {self.api_key}"}
            if self.api_key else {}
        )
        out = self._post(
            f"{self.base_url}/v1/chat/completions",
            {"model": self.model, "max_tokens": max_tokens,
             "temperature": temperature,
             "messages": [{"role": "user", "content": prompt}]},
            headers)
        return out["choices"][0]["message"]["content"]

    def generate_stream(self, prompt: str, max_tokens: int = 256,
                        temperature: float = 0.0) -> Iterator[str]:
        yield self.generate(prompt, max_tokens, temperature)


class OllamaGenerator(_HttpGenerator):
    """Ollama HTTP backend (reference: Ollama generator)."""

    def __init__(self, base_url: str = "http://127.0.0.1:11434",
                 model: str = "llama3", name: Optional[str] = None):
        self.base_url = base_url.rstrip("/")
        self.model = model
        self.name = name or f"ollama:{model}"

    def generate(self, prompt: str, max_tokens: int = 256,
                 temperature: float = 0.0) -> str:
        out = self._post(
            f"{self.base_url}/api/generate",
            {"model": self.model, "prompt": prompt, "stream": False,
             "options": {"num_predict": max_tokens,
                         "temperature": temperature}},
            {})
        return out.get("response", "")

    def generate_stream(self, prompt: str, max_tokens: int = 256,
                        temperature: float = 0.0) -> Iterator[str]:
        yield self.generate(prompt, max_tokens, temperature)
