"""Heimdall manager: model registry + scheduler + generation API.

Reference: pkg/heimdall/scheduler.go — Manager (:22,:52) owns a model
registry with VRAM estimates, loads/unloads against a memory budget, and
exposes Generate/GenerateStream/GenerateWithTools/Chat (:211,:241,:285,
:311). Here the budget models device HBM (the SLM and the vector
indexes share the chip) and loading is constructing the backend.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, List, Optional

from nornicdb_tpu.heimdall.generators import (
    EchoGenerator,
    Generator,
    JAXGenerator,
    Message,
    render_chat,
)


@dataclass
class ModelSpec:
    """Registry entry (reference: model registry with VRAM estimates)."""

    name: str
    backend: str = "jax"  # jax | openai | ollama | echo
    memory_bytes: int = 0  # HBM estimate; 0 = computed at load
    options: Dict[str, Any] = field(default_factory=dict)
    loaded: bool = False


@dataclass
class GenerationResult:
    text: str
    model: str
    took_ms: float
    tool_calls: List[Dict[str, Any]] = field(default_factory=list)


class Manager:
    """Loads models within an HBM budget and serves generation."""

    def __init__(self, memory_budget_bytes: int = 2 * 1024**3,
                 rbac_check: Optional[Callable[[Optional[str]], None]] = None):
        self._specs: Dict[str, ModelSpec] = {}
        self._loaded: Dict[str, Generator] = {}
        self._loading: Dict[str, threading.Event] = {}
        self._lock = threading.Lock()
        self.memory_budget = memory_budget_bytes
        self.memory_used = 0
        self._rbac_check = rbac_check
        self._plugins: List[Any] = []
        self.bifrost = None  # optional push channel (set by server wiring)

    # -- registry --------------------------------------------------------

    def register(self, spec: ModelSpec) -> None:
        with self._lock:
            self._specs[spec.name] = spec

    def models(self) -> List[ModelSpec]:
        with self._lock:
            return list(self._specs.values())

    def register_plugin(self, plugin: Any) -> None:
        """Heimdall plugins observe/transform generations
        (reference: plugin.go)."""
        self._plugins.append(plugin)

    # -- load/unload -----------------------------------------------------

    def _build(self, spec: ModelSpec) -> Generator:
        if spec.backend == "jax":
            gen = JAXGenerator(name=spec.name, **spec.options)
            if not spec.memory_bytes:
                spec.memory_bytes = gen.param_bytes()
            return gen
        if spec.backend == "openai":
            from nornicdb_tpu.heimdall.generators import OpenAIGenerator

            return OpenAIGenerator(name=spec.name, **spec.options)
        if spec.backend == "ollama":
            from nornicdb_tpu.heimdall.generators import OllamaGenerator

            return OllamaGenerator(name=spec.name, **spec.options)
        if spec.backend == "echo":
            return EchoGenerator(name=spec.name, **spec.options)
        raise ValueError(f"unknown backend {spec.backend!r}")

    def load(self, name: str) -> Generator:
        # per-name loading latch: two concurrent loads of the same model
        # must not both run _build (the second would allocate the model's
        # device memory again and double-count memory_used — a permanent
        # accounting leak causing spurious evictions)
        while True:
            with self._lock:
                if name in self._loaded:
                    return self._loaded[name]
                latch = self._loading.get(name)
                if latch is None:
                    spec = self._specs.get(name)
                    if spec is None:
                        raise KeyError(f"model {name!r} not registered")
                    latch = threading.Event()
                    self._loading[name] = latch
                    break
            latch.wait()  # another thread is building; retry once it's done
        try:
            gen = self._build(spec)
        except BaseException:
            with self._lock:
                del self._loading[name]
            latch.set()
            raise
        need = spec.memory_bytes
        with self._lock:
            del self._loading[name]
            latch.set()
            # evict least-recently-loaded models until it fits
            # (reference: scheduler evicts on VRAM pressure)
            while (self.memory_used + need > self.memory_budget
                   and self._loaded):
                evict_name, evicted = next(iter(self._loaded.items()))
                del self._loaded[evict_name]
                self._specs[evict_name].loaded = False
                self.memory_used -= self._specs[evict_name].memory_bytes
            if need > self.memory_budget:
                raise MemoryError(
                    f"model {name!r} needs {need} bytes > budget "
                    f"{self.memory_budget}")
            self._loaded[name] = gen
            spec.loaded = True
            self.memory_used += need
            return gen

    def unload(self, name: str) -> bool:
        with self._lock:
            if name not in self._loaded:
                return False
            del self._loaded[name]
            spec = self._specs[name]
            spec.loaded = False
            self.memory_used -= spec.memory_bytes
            return True

    def _default_model(self) -> str:
        with self._lock:
            if self._loaded:
                return next(iter(self._loaded))
            if self._specs:
                return next(iter(self._specs))
        raise RuntimeError("no models registered")

    # -- generation API (reference: scheduler.go:211-311) ----------------

    def generate(self, prompt: str, model: Optional[str] = None,
                 max_tokens: int = 256, temperature: float = 0.0,
                 user: Optional[str] = None) -> GenerationResult:
        if self._rbac_check is not None:
            self._rbac_check(user)
        name = model or self._default_model()
        gen = self.load(name)
        t0 = time.time()
        text = gen.generate(prompt, max_tokens=max_tokens,
                            temperature=temperature)
        for plugin in self._plugins:
            hook = getattr(plugin, "on_generate", None)
            if hook is not None:
                text = hook(prompt, text) or text
        result = GenerationResult(text=text, model=name,
                                  took_ms=(time.time() - t0) * 1e3)
        if self.bifrost is not None:
            self.bifrost.publish("generation", {
                "model": name, "prompt_chars": len(prompt),
                "output_chars": len(text)})
        return result

    def generate_stream(self, prompt: str, model: Optional[str] = None,
                        max_tokens: int = 256, temperature: float = 0.0,
                        user: Optional[str] = None) -> Iterator[str]:
        if self._rbac_check is not None:
            self._rbac_check(user)
        name = model or self._default_model()
        gen = self.load(name)
        yield from gen.generate_stream(prompt, max_tokens=max_tokens,
                                       temperature=temperature)

    def chat(self, messages: List[Message], model: Optional[str] = None,
             max_tokens: int = 256, temperature: float = 0.0,
             user: Optional[str] = None) -> GenerationResult:
        """OpenAI-compatible chat (reference: scheduler.go:311)."""
        return self.generate(render_chat(messages), model=model,
                             max_tokens=max_tokens, temperature=temperature,
                             user=user)

    def generate_with_tools(self, prompt: str, mcp, model: Optional[str] = None,
                            max_rounds: int = 4, max_tokens: int = 256,
                            user: Optional[str] = None) -> GenerationResult:
        """Streaming agentic tool loop executing MCP ops
        (reference: GenerateWithTools scheduler.go:285)."""
        from nornicdb_tpu.heimdall.tools import ToolLoop

        if self._rbac_check is not None:
            self._rbac_check(user)
        name = model or self._default_model()
        gen = self.load(name)
        loop = ToolLoop(gen, mcp, bifrost=self.bifrost)
        t0 = time.time()
        text, calls = loop.run(prompt, max_rounds=max_rounds,
                               max_tokens=max_tokens)
        return GenerationResult(text=text, model=name,
                                took_ms=(time.time() - t0) * 1e3,
                                tool_calls=calls)
