"""TPU-native SLM decoder: the compute core of the Heimdall subsystem.

Reference: pkg/heimdall runs reasoning SLMs next to the DB through
llama.cpp (types.go:1-60; local GGUF backend). The TPU replacement is a
JAX decoder-only transformer served in-process: static-shape prefill +
a KV-cache decode loop under ``lax.scan`` (no data-dependent Python
control flow inside jit), bfloat16 matmuls on the MXU, and a byte-level
tokenizer so the pipeline is fully self-contained (no vendored GGUF
weights in this image; weights load from an orbax/npz checkpoint when
provided, else random init — generation machinery, sampling, and
serving are identical either way).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# byte-level vocab: 256 bytes + PAD/BOS/EOS
PAD, BOS, EOS = 256, 257, 258
VOCAB = 259


@dataclass(frozen=True)
class DecoderConfig:
    vocab: int = VOCAB
    d_model: int = 256
    n_heads: int = 4
    n_layers: int = 4
    d_ff: int = 1024
    max_seq: int = 512

    @staticmethod
    def tiny() -> "DecoderConfig":
        return DecoderConfig(d_model=64, n_heads=2, n_layers=2, d_ff=128,
                             max_seq=128)


def encode_bytes(text: str, max_len: int) -> np.ndarray:
    ids = [BOS] + list(text.encode("utf-8"))[: max_len - 1]
    return np.asarray(ids, dtype=np.int32)


def decode_bytes(ids) -> str:
    bs = bytes(int(i) for i in ids if 0 <= int(i) < 256)
    return bs.decode("utf-8", errors="replace")


def init_params(cfg: DecoderConfig, seed: int = 0) -> Dict[str, Any]:
    rng = np.random.default_rng(seed)

    def w(*shape, scale=None):
        scale = scale or (1.0 / np.sqrt(shape[0]))
        return jnp.asarray(
            rng.standard_normal(shape, dtype=np.float32) * scale)

    layers = []
    for _ in range(cfg.n_layers):
        layers.append({
            "ln1": jnp.ones(cfg.d_model),
            "ln2": jnp.ones(cfg.d_model),
            "wq": w(cfg.d_model, cfg.d_model),
            "wk": w(cfg.d_model, cfg.d_model),
            "wv": w(cfg.d_model, cfg.d_model),
            "wo": w(cfg.d_model, cfg.d_model),
            "w1": w(cfg.d_model, cfg.d_ff),
            "w2": w(cfg.d_ff, cfg.d_model),
        })
    return {
        "embed": w(cfg.vocab, cfg.d_model, scale=0.02),
        "pos": w(cfg.max_seq, cfg.d_model, scale=0.02),
        "ln_f": jnp.ones(cfg.d_model),
        "layers": layers,
    }


def _rms_norm(x, g):
    return x * g * jax.lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True)
                                 + 1e-6)


def _attn(cfg: DecoderConfig, lp, x, k_cache, v_cache, pos_mask):
    """x: [T, D]; caches: [S, D] (S = max_seq). pos_mask: [T, S] allowed."""
    t = x.shape[0]
    h, dh = cfg.n_heads, cfg.d_model // cfg.n_heads
    q = (x @ lp["wq"]).reshape(t, h, dh)
    k = k_cache.reshape(-1, h, dh)
    v = v_cache.reshape(-1, h, dh)
    scores = jnp.einsum("thd,shd->hts", q, k) / jnp.sqrt(dh).astype(x.dtype)
    scores = jnp.where(pos_mask[None, :, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("hts,shd->thd", probs, v).reshape(t, cfg.d_model)
    return out @ lp["wo"]


def _block(cfg, lp, x, k_cache, v_cache, pos_mask):
    normed = _rms_norm(x, lp["ln1"])
    x = x + _attn(cfg, lp, normed, k_cache, v_cache, pos_mask)
    normed = _rms_norm(x, lp["ln2"])
    x = x + jax.nn.gelu(normed @ lp["w1"]) @ lp["w2"]
    return x


def forward_full(cfg: DecoderConfig, params, tokens: jnp.ndarray,
                 key_valid: jnp.ndarray):
    """Shared full-sequence forward used by BOTH inference prefill and
    training (heimdall/train.py) — one definition, so train-time and
    generation-time math cannot drift. tokens: [S] int32; key_valid: [S]
    bool (which key positions are real). Returns (all_logits [S, V],
    caches)."""
    s = cfg.max_seq
    x = params["embed"][tokens] + params["pos"]
    x = x.astype(jnp.bfloat16)
    positions = jnp.arange(s)
    causal = positions[None, :] <= positions[:, None]  # [T, S]
    mask = causal & (key_valid[None, :]
                     | (positions[None, :] == positions[:, None]))
    caches = []
    for lp in params["layers"]:
        lp16 = jax.tree_util.tree_map(lambda a: a.astype(jnp.bfloat16), lp)
        k = _rms_norm(x, lp16["ln1"]) @ lp16["wk"]
        v = _rms_norm(x, lp16["ln1"]) @ lp16["wv"]
        x = _block(cfg, lp16, x, k, v, mask)
        caches.append((k, v))
    x = _rms_norm(x, params["ln_f"].astype(jnp.bfloat16))
    logits = x @ params["embed"].astype(jnp.bfloat16).T
    return logits.astype(jnp.float32), caches


@functools.partial(jax.jit, static_argnames=("cfg",))
def prefill(cfg: DecoderConfig, params, tokens: jnp.ndarray,
            length: jnp.ndarray):
    """tokens: [max_seq] int32 (PAD-padded); length: scalar actual length.
    Returns (logits_at_last, caches) where caches[l] = (k [S,D], v [S,D])."""
    key_valid = jnp.arange(cfg.max_seq) < length
    logits, caches = forward_full(cfg, params, tokens, key_valid)
    return logits[length - 1], caches


@functools.partial(jax.jit, static_argnames=("cfg", "max_new"))
def generate_tokens(
    cfg: DecoderConfig,
    params,
    tokens: jnp.ndarray,  # [max_seq] PAD-padded prompt
    length: jnp.ndarray,  # scalar
    max_new: int,
    temperature: float,
    rng_key: jnp.ndarray,
) -> jnp.ndarray:
    """Sample up to max_new tokens after the prompt; returns [max_new]
    int32 (EOS-padded once EOS is hit). Static shapes throughout: the
    decode loop is a lax.scan over positions with the KV cache updated
    via dynamic_update_slice."""
    logits0, caches = prefill(cfg, params, tokens, length)
    params16 = jax.tree_util.tree_map(
        lambda a: a.astype(jnp.bfloat16), params)
    s = cfg.max_seq
    positions = jnp.arange(s)

    def sample(logits, key):
        logits = logits.at[PAD].set(-1e30)
        return jax.lax.cond(
            temperature <= 1e-6,
            lambda: jnp.argmax(logits).astype(jnp.int32),
            lambda: jax.random.categorical(
                key, logits / jnp.maximum(temperature, 1e-6)
            ).astype(jnp.int32),
        )

    def step(carry, key):
        logits, caches, pos, done = carry
        tok = sample(logits, key)
        tok = jnp.where(done, EOS, tok)
        done = done | (tok == EOS) | (pos >= s - 1)
        # single-token forward at position `pos`
        x = (params16["embed"][tok] + params16["pos"][pos])[None, :]
        new_caches = []
        mask = (positions[None, :] <= pos)  # [1, S]
        for lp, (k_c, v_c) in zip(params16["layers"], caches):
            normed = _rms_norm(x, lp["ln1"])
            k_new = normed @ lp["wk"]
            v_new = normed @ lp["wv"]
            k_c = jax.lax.dynamic_update_slice(k_c, k_new, (pos, 0))
            v_c = jax.lax.dynamic_update_slice(v_c, v_new, (pos, 0))
            x = _block(cfg, lp, x, k_c, v_c, mask)
            new_caches.append((k_c, v_c))
        x = _rms_norm(x, params16["ln_f"])
        next_logits = (x[0] @ params16["embed"].T).astype(jnp.float32)
        return (next_logits, new_caches, pos + 1, done), tok

    keys = jax.random.split(rng_key, max_new)
    (_, _, _, _), toks = jax.lax.scan(
        step, (logits0, caches, length, jnp.asarray(False)), keys)
    return toks


class DecoderModel:
    """Host-side wrapper: tokenize → device generate → detokenize."""

    def __init__(self, cfg: Optional[DecoderConfig] = None,
                 params: Optional[Dict[str, Any]] = None, seed: int = 0):
        self.cfg = cfg or DecoderConfig.tiny()
        self.params = params if params is not None else init_params(
            self.cfg, seed)

    def param_bytes(self) -> int:
        leaves = jax.tree_util.tree_leaves(self.params)
        return int(sum(np.prod(x.shape) * 4 for x in leaves))

    def generate(self, prompt: str, max_tokens: int = 64,
                 temperature: float = 0.0, seed: int = 0) -> str:
        ids = encode_bytes(prompt, self.cfg.max_seq)
        length = len(ids)
        padded = np.full(self.cfg.max_seq, PAD, np.int32)
        padded[:length] = ids
        max_new = min(max_tokens, self.cfg.max_seq - length)
        if max_new <= 0:
            return ""
        toks = generate_tokens(
            self.cfg, self.params, jnp.asarray(padded),
            jnp.asarray(length, jnp.int32), int(max_new),
            float(temperature), jax.random.PRNGKey(seed),
        )
        out = np.asarray(toks)
        eos = np.nonzero(out == EOS)[0]
        if eos.size:
            out = out[: eos[0]]
        return decode_bytes(out)
