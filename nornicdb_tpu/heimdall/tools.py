"""Agentic tool loop: the model drives MCP tools between generations.

Reference: pkg/heimdall GenerateWithTools (scheduler.go:285) — a
streaming loop where the SLM emits tool invocations, the runtime
executes them against the DB's MCP ops (store/recall/discover/link/
cypher), and results feed back into the context until the model answers.

Protocol (prompted, model-agnostic): the model emits a line
``TOOL {"tool": "recall", "args": {"query": "..."}}``; anything else is
the final answer. Each round publishes a Bifrost event so UIs can
stream the agent's progress.
"""

from __future__ import annotations

import json
import re
from typing import Any, Dict, List, Optional, Tuple

_TOOL_RE = re.compile(r"^\s*TOOL\s+(\{.*\})\s*$", re.MULTILINE | re.DOTALL)

_SYSTEM = """You can call database tools. To call one, reply with a single
line: TOOL {"tool": "<name>", "args": {...}}
Available tools: %s
When you have the answer, reply with plain text (no TOOL line)."""


class ToolLoop:
    def __init__(self, generator, mcp, bifrost=None):
        self.generator = generator
        self.mcp = mcp
        self.bifrost = bifrost

    def _tool_names(self) -> List[str]:
        return sorted(self.mcp._tools.keys())

    def _execute(self, name: str, args: Dict[str, Any]) -> Any:
        handler = self.mcp._handlers.get(name)
        if handler is None:
            return {"error": f"unknown tool {name!r}"}
        try:
            return handler(args or {})
        except Exception as e:
            return {"error": f"{type(e).__name__}: {e}"}

    def run(self, prompt: str, max_rounds: int = 4,
            max_tokens: int = 256) -> Tuple[str, List[Dict[str, Any]]]:
        context = (_SYSTEM % ", ".join(self._tool_names())
                   + f"\n\nuser: {prompt}\nassistant:")
        calls: List[Dict[str, Any]] = []
        text = ""
        for round_no in range(max_rounds):
            text = self.generator.generate(context, max_tokens=max_tokens)
            m = _TOOL_RE.search(text or "")
            if m is None:
                break
            try:
                req = json.loads(m.group(1))
            except json.JSONDecodeError:
                break  # malformed tool call: treat as final text
            name = req.get("tool", "")
            args = req.get("args") or {}
            result = self._execute(name, args)
            calls.append({"tool": name, "args": args, "result": result})
            if self.bifrost is not None:
                self.bifrost.publish("tool_call", {
                    "round": round_no, "tool": name, "args": args})
            context += (
                f" TOOL {json.dumps(req)}\n"
                f"tool_result: {json.dumps(result, default=str)[:2000]}\n"
                "assistant:"
            )
        return (text or "").strip(), calls
