"""Real-weight import path for the Heimdall SLM: LLaMA-class → JAX.

The reference serves real reasoning SLMs (llama.cpp GGUF weights,
pkg/heimdall/scheduler.go:22, pkg/localllm) — Qwen/LLaMA-family
decoders. This image has no network, so the equivalent here is the same
pattern models/hf_import.py uses for the encoder: a LLaMA-architecture-
faithful JAX forward (RMSNorm, rotary embeddings, SwiGLU, grouped-query
attention, no biases) plus a state-dict importer, validated numerically
against transformers' torch LlamaForCausalLM with RANDOM weights at a
shape-real config (tests/test_heimdall_hf_import.py). The day real SLM
weights are reachable: point NORNICDB_TPU_SLM_DIR at the model
directory and Heimdall serves them.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np


@dataclass(frozen=True)
class HFDecoderConfig:
    vocab_size: int
    hidden_size: int
    num_layers: int
    num_heads: int
    num_kv_heads: int
    intermediate_size: int
    max_position: int
    rope_theta: float = 10000.0
    rms_eps: float = 1e-6
    tie_word_embeddings: bool = False

    @staticmethod
    def from_hf_config(cfg: Dict[str, Any]) -> "HFDecoderConfig":
        return HFDecoderConfig(
            vocab_size=int(cfg["vocab_size"]),
            hidden_size=int(cfg["hidden_size"]),
            num_layers=int(cfg["num_hidden_layers"]),
            num_heads=int(cfg["num_attention_heads"]),
            num_kv_heads=int(cfg.get("num_key_value_heads",
                                     cfg["num_attention_heads"])),
            intermediate_size=int(cfg["intermediate_size"]),
            max_position=int(cfg.get("max_position_embeddings", 2048)),
            rope_theta=float(cfg.get("rope_theta", 10000.0)),
            rms_eps=float(cfg.get("rms_norm_eps", 1e-6)),
            tie_word_embeddings=bool(cfg.get("tie_word_embeddings", False)),
        )


def import_hf_decoder_params(
    tensors: Dict[str, np.ndarray], cfg: HFDecoderConfig
) -> Dict[str, Any]:
    """Map a HF LLaMA-family state dict onto the JAX param tree.
    Raises KeyError naming the missing tensor."""
    pre = ""
    if any(k.startswith("model.") for k in tensors):
        pre = "model."

    def t(name: str, transpose: bool = False) -> jnp.ndarray:
        full = pre + name
        if full not in tensors:
            raise KeyError(f"checkpoint missing tensor {full!r}")
        arr = np.asarray(tensors[full], np.float32)
        return jnp.asarray(arr.T if transpose else arr)

    layers = []
    for i in range(cfg.num_layers):
        p = f"layers.{i}."
        layers.append({
            "ln1": t(p + "input_layernorm.weight"),
            "ln2": t(p + "post_attention_layernorm.weight"),
            # torch Linear [out, in] -> right-multiply [in, out]
            "wq": t(p + "self_attn.q_proj.weight", transpose=True),
            "wk": t(p + "self_attn.k_proj.weight", transpose=True),
            "wv": t(p + "self_attn.v_proj.weight", transpose=True),
            "wo": t(p + "self_attn.o_proj.weight", transpose=True),
            "w_gate": t(p + "mlp.gate_proj.weight", transpose=True),
            "w_up": t(p + "mlp.up_proj.weight", transpose=True),
            "w_down": t(p + "mlp.down_proj.weight", transpose=True),
        })
    embed = t("embed_tokens.weight")
    if cfg.tie_word_embeddings or "lm_head.weight" not in tensors:
        lm_head = embed.T
    else:
        lm_head = jnp.asarray(
            np.asarray(tensors["lm_head.weight"], np.float32).T)
    return {
        "embed": embed,
        "norm": t("norm.weight"),
        "lm_head": lm_head,
        "layers": layers,
    }


def _rms(x: jnp.ndarray, g: jnp.ndarray, eps: float) -> jnp.ndarray:
    return (x * jax.lax.rsqrt(
        jnp.mean(x * x, axis=-1, keepdims=True) + eps)) * g


def _rope(x: jnp.ndarray, positions: jnp.ndarray,
          theta: float) -> jnp.ndarray:
    """LLaMA rotary embedding over [T, H, Dh] (half-split convention:
    rotate the first half against the second, matching HF's
    rotate_half)."""
    dh = x.shape[-1]
    half = dh // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    ang = positions[:, None].astype(jnp.float32) * freqs[None, :]  # [T, half]
    cos = jnp.cos(ang)[:, None, :]
    sin = jnp.sin(ang)[:, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)


def forward(cfg: HFDecoderConfig, params: Dict[str, Any],
            token_ids: jnp.ndarray) -> jnp.ndarray:
    """[T] int32 -> [T, vocab] logits (causal, full prefill)."""
    t = token_ids.shape[0]
    h, kvh = cfg.num_heads, cfg.num_kv_heads
    dh = cfg.hidden_size // h
    positions = jnp.arange(t)
    causal = positions[:, None] >= positions[None, :]
    x = params["embed"][token_ids]
    for lp in params["layers"]:
        y = _rms(x, lp["ln1"], cfg.rms_eps)
        q = _rope((y @ lp["wq"]).reshape(t, h, dh), positions,
                  cfg.rope_theta)
        k = _rope((y @ lp["wk"]).reshape(t, kvh, dh), positions,
                  cfg.rope_theta)
        v = (y @ lp["wv"]).reshape(t, kvh, dh)
        if kvh != h:  # grouped-query attention: repeat kv heads
            rep = h // kvh
            k = jnp.repeat(k, rep, axis=1)
            v = jnp.repeat(v, rep, axis=1)
        scores = jnp.einsum("thd,shd->hts", q, k) / np.sqrt(dh)
        scores = jnp.where(causal[None, :, :], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        attn = jnp.einsum("hts,shd->thd", probs, v).reshape(
            t, cfg.hidden_size)
        x = x + attn @ lp["wo"]
        y = _rms(x, lp["ln2"], cfg.rms_eps)
        x = x + (jax.nn.silu(y @ lp["w_gate"]) * (y @ lp["w_up"])) \
            @ lp["w_down"]
    x = _rms(x, params["norm"], cfg.rms_eps)
    return x @ params["lm_head"]


_WEIGHT_FILES = ("model.safetensors", "pytorch_model.bin", "model.npz")


def load_hf_decoder_dir(model_dir: str):
    """(cfg, params) from a local HF LLaMA-family model directory."""
    with open(os.path.join(model_dir, "config.json"), encoding="utf-8") as f:
        cfg = HFDecoderConfig.from_hf_config(json.load(f))
    from nornicdb_tpu.models.hf_import import read_checkpoint_tensors

    for fname in _WEIGHT_FILES:
        path = os.path.join(model_dir, fname)
        if os.path.exists(path):
            return cfg, import_hf_decoder_params(
                read_checkpoint_tensors(path), cfg)
    raise FileNotFoundError(
        f"no weight file in {model_dir!r} (looked for {_WEIGHT_FILES})")


class HFDecoderModel:
    """DecoderModel-interface wrapper over imported LLaMA-class weights
    (heimdall/generators.py JaxGenerator-compatible: generate())."""

    def __init__(self, model_dir: str):
        import threading

        self.cfg, self.params = load_hf_decoder_dir(model_dir)
        from transformers import AutoTokenizer

        self.tokenizer = AutoTokenizer.from_pretrained(
            model_dir, local_files_only=True)
        self._fwd = jax.jit(
            lambda p, ids: forward(self.cfg, p, ids))
        self._lock = threading.Lock()

    def param_bytes(self) -> int:
        return sum(int(np.prod(v.shape)) * 4
                   for v in jax.tree_util.tree_leaves(self.params))

    def generate(self, prompt: str, max_tokens: int = 64,
                 temperature: float = 0.0, seed: int = 0) -> str:
        """Greedy (temperature 0) or sampled decode. Re-runs the full
        prefill per step — fine for the SLM tool-loop scale; a KV-cache
        scan is the TPU-serving upgrade path."""
        ids: List[int] = self.tokenizer.encode(prompt)
        rng = np.random.default_rng(seed)
        eos = self.tokenizer.eos_token_id
        out: List[int] = []
        with self._lock:
            for _ in range(max_tokens):
                window = ids[-self.cfg.max_position:]
                logits = np.asarray(self._fwd(
                    self.params, jnp.asarray(window, jnp.int32)))[-1]
                if temperature and temperature > 0:
                    z = logits / temperature
                    z = z - z.max()
                    p = np.exp(z) / np.exp(z).sum()
                    nxt = int(rng.choice(len(p), p=p))
                else:
                    nxt = int(np.argmax(logits))
                if eos is not None and nxt == eos:
                    break
                ids.append(nxt)
                out.append(nxt)
        return self.tokenizer.decode(out)


def default_slm_dir() -> Optional[str]:
    """NORNICDB_TPU_SLM_DIR when it points at a loadable model dir."""
    d = os.environ.get("NORNICDB_TPU_SLM_DIR", "")
    if d and os.path.exists(os.path.join(d, "config.json")):
        return d
    return None
