"""Bifrost: the push channel streaming Heimdall events to clients.

Reference: pkg/heimdall/bifrost.go:15,42 — SSE/WebSocket push channel.
Here: a thread-safe pub/sub hub with bounded per-subscriber queues plus
an SSE rendering helper used by the HTTP server (GET /bifrost/events).
Slow subscribers drop oldest events rather than blocking publishers.
"""

from __future__ import annotations

import json
import queue
import threading
import time
from typing import Any, Dict, Iterator, Optional


class Bifrost:
    def __init__(self, max_queue: int = 256):
        self._subs: Dict[int, "queue.Queue[dict]"] = {}
        self._next = 0
        self._lock = threading.Lock()
        self.max_queue = max_queue
        self.events_published = 0

    def subscribe(self) -> int:
        with self._lock:
            sid = self._next
            self._next += 1
            self._subs[sid] = queue.Queue(maxsize=self.max_queue)
            return sid

    def unsubscribe(self, sid: int) -> None:
        with self._lock:
            self._subs.pop(sid, None)

    def publish(self, event: str, data: Dict[str, Any]) -> int:
        """Fan out to all subscribers; never blocks (drops oldest)."""
        msg = {"event": event, "data": data, "ts": time.time()}
        with self._lock:
            subs = list(self._subs.values())
            self.events_published += 1
        for q in subs:
            try:
                q.put_nowait(msg)
            except queue.Full:
                try:
                    q.get_nowait()
                    q.put_nowait(msg)
                except (queue.Empty, queue.Full):
                    pass
        return len(subs)

    def events(self, sid: int, timeout: float = 1.0,
               max_events: Optional[int] = None) -> Iterator[dict]:
        """Drain events for a subscriber; stops on timeout gaps."""
        q = self._subs.get(sid)
        if q is None:
            return
        n = 0
        while max_events is None or n < max_events:
            try:
                yield q.get(timeout=timeout)
                n += 1
            except queue.Empty:
                return

    @staticmethod
    def sse(msg: dict) -> str:
        """Render one event in Server-Sent Events wire format."""
        return (f"event: {msg['event']}\n"
                f"data: {json.dumps(msg['data'], default=str)}\n\n")
