"""Tiny in-repo training for the Heimdall decoder.

Round-1 verdict: the SLM subsystem was "plumbing-complete but
capability-empty" (random weights). This module trains the byte-level
decoder (heimdall/model.py) with next-byte cross-entropy so a small,
committed checkpoint makes `generate()` deterministic and meaningful —
the TPU-native analog of the reference shipping a GGUF model for its SLM
(pkg/heimdall + pkg/localllm vendored llama.cpp weights).

Checkpoints are flat .npz files (save_params/load_params) consumable by
DecoderModel/JaxGenerator.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from nornicdb_tpu.heimdall.model import (
    BOS,
    EOS,
    PAD,
    DecoderConfig,
    init_params,
)


def sequence_logits(cfg: DecoderConfig, params, tokens: jnp.ndarray):
    """Logits for every position via the model's OWN forward
    (model.forward_full) — train-time math is inference-time math by
    construction. tokens: [B, S] int32 (PAD-padded)."""
    from nornicdb_tpu.heimdall.model import forward_full

    def one(seq):
        logits, _caches = forward_full(cfg, params, seq, seq != PAD)
        return logits

    return jax.vmap(one)(tokens)


def _loss_fn(cfg: DecoderConfig, params, batch: jnp.ndarray) -> jnp.ndarray:
    logits = sequence_logits(cfg, params, batch)  # [B, S, V]
    targets = jnp.roll(batch, -1, axis=1)
    mask = (batch != PAD) & (targets != PAD)
    mask = mask.at[:, -1].set(False)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return (nll * mask).sum() / jnp.maximum(mask.sum(), 1)


def encode_corpus(lines: Iterable[str], cfg: DecoderConfig) -> np.ndarray:
    """Each line becomes one PAD-padded row: BOS + bytes + EOS — the
    exact framing model.encode_bytes uses at generation time (a BOS
    mismatch here trains a model that babbles at inference)."""
    rows = []
    for line in lines:
        ids = [BOS] + list(line.encode("utf-8"))[: cfg.max_seq - 2] + [EOS]
        row = np.full(cfg.max_seq, PAD, np.int32)
        row[: len(ids)] = ids
        rows.append(row)
    return np.stack(rows)


def train(
    corpus: List[str],
    cfg: Optional[DecoderConfig] = None,
    steps: int = 300,
    lr: float = 3e-3,
    batch_size: int = 16,
    seed: int = 0,
    log_every: int = 0,
) -> Tuple[Dict[str, Any], float]:
    """Adam training loop; returns (params, final_loss)."""
    import optax

    cfg = cfg or DecoderConfig.tiny()
    params = init_params(cfg, seed)
    data = encode_corpus(corpus, cfg)
    opt = optax.adam(lr)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(
            lambda p: _loss_fn(cfg, p, batch))(params)
        updates, opt_state = opt.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    rng = np.random.default_rng(seed)
    loss = float("nan")
    for i in range(steps):
        idx = rng.integers(0, len(data), min(batch_size, len(data)))
        params, opt_state, loss_j = step(params, opt_state,
                                         jnp.asarray(data[idx]))
        loss = float(loss_j)
        if log_every and (i + 1) % log_every == 0:
            print(f"step {i + 1}/{steps} loss {loss:.4f}")
    return params, loss


# -- checkpoint io --------------------------------------------------------


def save_params(path: str, cfg: DecoderConfig, params: Dict[str, Any]) -> None:
    flat = {
        "cfg.vocab": cfg.vocab,
        "cfg.d_model": cfg.d_model,
        "cfg.n_layers": cfg.n_layers,
        "cfg.n_heads": cfg.n_heads,
        "cfg.d_ff": cfg.d_ff,
        "cfg.max_seq": cfg.max_seq,
        "embed": np.asarray(params["embed"], np.float32),
        "pos": np.asarray(params["pos"], np.float32),
        "ln_f": np.asarray(params["ln_f"], np.float32),
    }
    for i, lp in enumerate(params["layers"]):
        for k, v in lp.items():
            flat[f"layer{i}.{k}"] = np.asarray(v, np.float32)
    with open(path, "wb") as f:
        np.savez_compressed(f, **flat)


def load_params(path: str) -> Tuple[DecoderConfig, Dict[str, Any]]:
    data = np.load(path, allow_pickle=False)
    cfg = DecoderConfig(
        vocab=int(data["cfg.vocab"]), d_model=int(data["cfg.d_model"]),
        n_layers=int(data["cfg.n_layers"]), n_heads=int(data["cfg.n_heads"]),
        d_ff=int(data["cfg.d_ff"]), max_seq=int(data["cfg.max_seq"]),
    )
    layers = []
    for i in range(cfg.n_layers):
        prefix = f"layer{i}."
        layers.append({
            k[len(prefix):]: jnp.asarray(data[k])
            for k in data.files if k.startswith(prefix)
        })
    params = {
        "embed": jnp.asarray(data["embed"]),
        "pos": jnp.asarray(data["pos"]),
        "ln_f": jnp.asarray(data["ln_f"]),
        "layers": layers,
    }
    return cfg, params


def default_checkpoint_path() -> Optional[str]:
    """Path of the committed tiny checkpoint, or None if absent."""
    import os

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "checkpoints", "heimdall_tiny.npz")
    return path if os.path.exists(path) else None


DEFAULT_CORPUS = [
    "nornicdb is a tpu-native graph database.",
    "heimdall watches the graph and answers questions.",
    "store memories, link them, and recall them later.",
    "vector search runs on the tpu matrix unit.",
    "the write-ahead log keeps every mutation durable.",
    "cypher queries match patterns over nodes and edges.",
    "embeddings are indexed for hybrid search.",
    "the decay manager ages episodic memories.",
]


def main() -> None:  # pragma: no cover
    """CLI: python -m nornicdb_tpu.heimdall.train <out.npz> [steps]"""
    import sys

    out = sys.argv[1] if len(sys.argv) > 1 else "heimdall_tiny.npz"
    steps = int(sys.argv[2]) if len(sys.argv) > 2 else 600
    cfg = DecoderConfig.tiny()
    params, loss = train(DEFAULT_CORPUS, cfg, steps=steps, log_every=50)
    save_params(out, cfg, params)
    print(f"saved {out} (final loss {loss:.4f})")


if __name__ == "__main__":  # pragma: no cover
    main()
