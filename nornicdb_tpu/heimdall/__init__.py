"""Heimdall: the "cognitive guardian" — reasoning SLMs next to the DB.

Reference: pkg/heimdall (types.go:1-60, scheduler.go:22-311 Manager with
Generate/GenerateStream/GenerateWithTools/Chat, bifrost.go push channel,
plugin.go). The TPU build replaces the llama.cpp GGUF backends with an
in-process JAX decoder (heimdall/model.py) plus HTTP generator backends,
a model registry/scheduler with HBM estimates, a streaming agentic tool
loop over the MCP tools, and the Bifrost SSE push channel.
"""

from nornicdb_tpu.heimdall.scheduler import (
    GenerationResult,
    Manager,
    ModelSpec,
)
from nornicdb_tpu.heimdall.generators import (
    EchoGenerator,
    Generator,
    JAXGenerator,
    OllamaGenerator,
    OpenAIGenerator,
)
from nornicdb_tpu.heimdall.bifrost import Bifrost
from nornicdb_tpu.heimdall.tools import ToolLoop

__all__ = [
    "Bifrost",
    "EchoGenerator",
    "GenerationResult",
    "Generator",
    "JAXGenerator",
    "Manager",
    "ModelSpec",
    "OllamaGenerator",
    "OpenAIGenerator",
    "ToolLoop",
]
