"""Tokenization + chunking for the embedding pipeline.

The reference chunks long documents at 512 tokens with 50-token overlap
(pkg/nornicdb/db.go:1046-1047; embed_queue.go:774 embedChunksInBatches).
Without network access to real bge-m3 vocab files, the default tokenizer
hashes whitespace/punctuation-split subwords into a fixed id space — fully
deterministic, vocabulary-free, and adequate for the encoder until real
weights/vocab are loaded (the Embedder interface hides the choice).
"""

from __future__ import annotations

import hashlib
import re
from typing import List, Sequence, Tuple

_WORD_RE = re.compile(r"[a-z0-9]+|[^\sa-z0-9]")

CHUNK_SIZE = 512
CHUNK_OVERLAP = 50


class HashTokenizer:
    """Deterministic hash tokenizer: token -> stable id in [2, vocab)."""

    PAD_ID = 0
    CLS_ID = 1

    def __init__(self, vocab_size: int = 30522):
        self.vocab_size = vocab_size

    def encode(self, text: str, max_len: int = CHUNK_SIZE) -> List[int]:
        ids = [self.CLS_ID]
        for tok in _WORD_RE.findall(text.lower()):
            h = int.from_bytes(
                hashlib.blake2s(tok.encode("utf-8"), digest_size=4).digest(),
                "little",
            )
            ids.append(2 + h % (self.vocab_size - 2))
            if len(ids) >= max_len:
                break
        return ids

    def encode_batch(
        self, texts: Sequence[str], max_len: int = CHUNK_SIZE
    ) -> Tuple[List[List[int]], int]:
        """Returns (padded id lists, width)."""
        encoded = [self.encode(t, max_len) for t in texts]
        width = max((len(e) for e in encoded), default=1)
        return [e + [self.PAD_ID] * (width - len(e)) for e in encoded], width


def chunk_tokens(
    ids: List[int],
    chunk_size: int = CHUNK_SIZE,
    overlap: int = CHUNK_OVERLAP,
) -> List[List[int]]:
    """Sliding-window chunking (512/50 default, reference db.go:1046)."""
    if len(ids) <= chunk_size:
        return [ids]
    step = max(chunk_size - overlap, 1)
    chunks = []
    for start in range(0, len(ids), step):
        chunk = ids[start : start + chunk_size]
        if not chunk:
            break
        chunks.append(chunk)
        if start + chunk_size >= len(ids):
            break
    return chunks


def chunk_text(
    text: str,
    tokenizer: HashTokenizer,
    chunk_size: int = CHUNK_SIZE,
    overlap: int = CHUNK_OVERLAP,
) -> List[List[int]]:
    ids = tokenizer.encode(text, max_len=1_000_000)
    return chunk_tokens(ids, chunk_size, overlap)
