"""HTTP embedding providers: Ollama and OpenAI-compatible endpoints.

Reference: pkg/embed/embed.go — NewOllama (:342, POST /api/embeddings
{"model","prompt"} -> {"embedding":[...]}) and NewOpenAI (:640, POST
/v1/embeddings {"model","input":[...]} -> {"data":[{"embedding"}...]}
with Bearer auth), both with timeouts and bounded retries. Providers
implement the same Embedder protocol as the local embedders
(embed/embedder.py) so they slot into the embed queue unchanged.
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional, Sequence


class EmbedHTTPError(RuntimeError):
    """Provider request failed after retries."""


def _post_json(url: str, payload: Dict[str, Any],
               headers: Optional[Dict[str, str]] = None,
               timeout: float = 30.0, retries: int = 2,
               backoff_s: float = 0.5) -> Dict[str, Any]:
    body = json.dumps(payload).encode("utf-8")
    hdrs = {"Content-Type": "application/json", **(headers or {})}
    last: Optional[Exception] = None
    for attempt in range(retries + 1):
        try:
            req = urllib.request.Request(url, data=body, headers=hdrs,
                                         method="POST")
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                raw = resp.read()
            try:
                return json.loads(raw)
            except json.JSONDecodeError as e:
                # a 200 with a non-JSON body (proxy error page) is as
                # transient as a 5xx — retry, then wrap
                if attempt == retries:
                    raise EmbedHTTPError(
                        f"POST {url} returned non-JSON body: "
                        f"{raw[:200]!r}") from e
                last = e
                time.sleep(backoff_s * (attempt + 1))
                continue
        except urllib.error.HTTPError as e:
            # 4xx are permanent (bad model name, auth); 5xx retry
            detail = ""
            try:
                detail = e.read().decode("utf-8", "replace")[:300]
            except Exception:
                pass
            if e.code < 500 or attempt == retries:
                raise EmbedHTTPError(
                    f"POST {url} -> HTTP {e.code}: {detail}") from e
            last = e
        except (urllib.error.URLError, TimeoutError, OSError) as e:
            last = e
            if attempt == retries:
                raise EmbedHTTPError(f"POST {url} failed: {e}") from e
        time.sleep(backoff_s * (attempt + 1))
    raise EmbedHTTPError(f"POST {url} failed: {last}")


class OllamaEmbedder:
    """Local Ollama server (reference: embed.go:342 NewOllama; request
    shape ollamaRequest{model,prompt} -> ollamaResponse{embedding})."""

    def __init__(self, base_url: str = "http://localhost:11434",
                 model: str = "nomic-embed-text",
                 timeout: float = 30.0, retries: int = 2):
        self.base_url = base_url.rstrip("/")
        self.model = model
        self.timeout = timeout
        self.retries = retries
        self._dims: Optional[int] = None

    def embed(self, text: str) -> List[float]:
        doc = _post_json(
            f"{self.base_url}/api/embeddings",
            {"model": self.model, "prompt": text},
            timeout=self.timeout, retries=self.retries,
        )
        emb = doc.get("embedding")
        if not isinstance(emb, list) or not emb:
            raise EmbedHTTPError(
                f"ollama returned no embedding (model {self.model!r})")
        self._dims = len(emb)
        return [float(x) for x in emb]

    def embed_batch(self, texts: Sequence[str]) -> List[List[float]]:
        return [self.embed(t) for t in texts]

    @property
    def dims(self) -> Optional[int]:
        """Provider dimension, discovered from the first embedding (the
        server owns the model config; None until the first call)."""
        return self._dims


class OpenAIEmbedder:
    """OpenAI-compatible /embeddings endpoint (reference: embed.go:640
    NewOpenAI). Works with any server speaking the same contract
    (vLLM, LM Studio, llama.cpp server, Azure with base_url override)."""

    def __init__(self, api_key: str = "",
                 base_url: str = "https://api.openai.com/v1",
                 model: str = "text-embedding-3-small",
                 timeout: float = 30.0, retries: int = 2,
                 batch_size: int = 128):
        self.api_key = api_key
        self.base_url = base_url.rstrip("/")
        self.model = model
        self.timeout = timeout
        self.retries = retries
        self.batch_size = max(1, batch_size)
        self._dims: Optional[int] = None

    @property
    def dims(self) -> Optional[int]:
        """Discovered from the first embedding; None until then."""
        return self._dims

    def _headers(self) -> Dict[str, str]:
        h = {}
        if self.api_key:
            h["Authorization"] = f"Bearer {self.api_key}"
        return h

    def embed_batch(self, texts: Sequence[str]) -> List[List[float]]:
        out: List[List[float]] = []
        for i in range(0, len(texts), self.batch_size):
            chunk = list(texts[i:i + self.batch_size])
            doc = _post_json(
                f"{self.base_url}/embeddings",
                {"model": self.model, "input": chunk},
                headers=self._headers(),
                timeout=self.timeout, retries=self.retries,
            )
            data = doc.get("data")
            if not isinstance(data, list) or len(data) != len(chunk):
                raise EmbedHTTPError(
                    f"openai returned {len(data or [])} embeddings for "
                    f"{len(chunk)} inputs")
            # the API may reorder; index field is authoritative
            ordered: List[Optional[List[float]]] = [None] * len(chunk)
            try:
                for item in data:
                    ordered[int(item["index"])] = [
                        float(x) for x in item["embedding"]
                    ]
            except (KeyError, IndexError, TypeError, ValueError) as e:
                raise EmbedHTTPError(
                    f"malformed embedding item in response: {e}") from e
            if any(v is None for v in ordered):
                raise EmbedHTTPError("openai response missing indices")
            out.extend(ordered)  # type: ignore[arg-type]
        if out:
            self._dims = len(out[0])
        return out

    def embed(self, text: str) -> List[float]:
        return self.embed_batch([text])[0]


def make_http_embedder(provider: str, **kw) -> Any:
    """Factory mirroring the reference's NewEmbedder provider switch
    (embed.go:816)."""
    provider = provider.lower()
    if provider == "ollama":
        return OllamaEmbedder(**kw)
    if provider in ("openai", "openai-compatible"):
        return OpenAIEmbedder(**kw)
    raise ValueError(f"unknown embedding provider {provider!r}")
