"""Embedder implementations.

Reference: pkg/embed — ``Embedder`` interface (embed.go:71), the local
GGUF/llama.cpp provider (local_gguf.go:57) with crash recovery, and the
cached decorator (cached_embedder.go). The TPU-native local provider is
``JaxEncoderEmbedder``: the flax encoder jitted once per (batch, width)
bucket, batched, padded to stable shapes so XLA never recompiles.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import List, Optional, Protocol, Sequence

import numpy as np

from nornicdb_tpu.embed.tokenizer import CHUNK_OVERLAP, CHUNK_SIZE, HashTokenizer, chunk_tokens


class Embedder(Protocol):
    dims: int

    def embed(self, text: str) -> List[float]: ...

    def embed_batch(self, texts: Sequence[str]) -> List[List[float]]: ...


class HashEmbedder:
    """Deterministic, dependency-free embedder (test double + offline
    default). Token-hash bag-of-features, L2-normalized — similar texts
    share tokens, so cosine behaves sensibly."""

    def __init__(self, dims: int = 256):
        self.dims = dims
        self._tok = HashTokenizer(vocab_size=1 << 22)

    def embed(self, text: str) -> List[float]:
        v = np.zeros(self.dims, dtype=np.float32)
        ids = self._tok.encode(text, max_len=4096)[1:]  # drop CLS
        for tid in ids:
            v[tid % self.dims] += 1.0
            v[(tid >> 8) % self.dims] += 0.5
        n = np.linalg.norm(v)
        if n > 1e-12:
            v /= n
        return v.tolist()

    def embed_batch(self, texts: Sequence[str]) -> List[List[float]]:
        return [self.embed(t) for t in texts]


class JaxEncoderEmbedder:
    """Local TPU embedder over the flax encoder.

    - pads token widths to power-of-two buckets (jit cache stays small);
    - batches up to ``max_batch`` texts per device call;
    - long texts are chunked 512/50 and mean-pooled (whole-doc vector);
      per-chunk vectors available via embed_chunks (reference
      ChunkEmbeddings, db.go:224).
    """

    def __init__(
        self,
        model=None,
        params=None,
        cfg=None,
        max_batch: int = 64,
        seed: int = 0,
    ):
        import jax

        from nornicdb_tpu.models.encoder import Encoder, EncoderConfig

        if cfg is None:
            from nornicdb_tpu.models.encoder import flash_attention_enabled

            cfg = EncoderConfig(
                use_flash_attention=flash_attention_enabled())
        if model is None:
            model = Encoder(cfg)
        if params is None:
            params = model.init(
                jax.random.PRNGKey(seed),
                np.ones((1, 8), np.int32),
            )["params"]
        self.cfg = cfg
        self.model = model
        self.params = params
        self.dims = cfg.hidden_size
        self.max_batch = max_batch
        self.tokenizer = HashTokenizer(cfg.vocab_size)
        self._jit = jax.jit(
            lambda p, ids: model.apply({"params": p}, ids)
        )
        self._lock = threading.Lock()

    @staticmethod
    def _bucket_width(w: int) -> int:
        b = 16
        while b < w:
            b *= 2
        return b

    def _run(self, id_lists: List[List[int]]) -> np.ndarray:
        import jax.numpy as jnp

        width = self._bucket_width(max(len(x) for x in id_lists))
        width = min(width, self.cfg.max_len)
        arr = np.zeros((len(id_lists), width), np.int32)
        for i, ids in enumerate(id_lists):
            ids = ids[:width]
            arr[i, : len(ids)] = ids
        with self._lock:
            out = self._jit(self.params, jnp.asarray(arr))
        return np.asarray(out, dtype=np.float32)

    def embed_batch(self, texts: Sequence[str]) -> List[List[float]]:
        out: List[List[float]] = []
        for start in range(0, len(texts), self.max_batch):
            batch = texts[start : start + self.max_batch]
            id_lists = [
                self.tokenizer.encode(t, max_len=self.cfg.max_len) for t in batch
            ]
            vecs = self._run(id_lists)
            out.extend(v.tolist() for v in vecs)
        return out

    def embed(self, text: str) -> List[float]:
        return self.embed_batch([text])[0]

    def embed_chunks(self, text: str) -> List[List[float]]:
        """Per-chunk embeddings for long documents (512/50 windows)."""
        ids = self.tokenizer.encode(text, max_len=1_000_000)
        chunks = chunk_tokens(
            ids, min(CHUNK_SIZE, self.cfg.max_len), CHUNK_OVERLAP
        )
        vecs: List[List[float]] = []
        for start in range(0, len(chunks), self.max_batch):
            vecs.extend(
                v.tolist() for v in self._run(chunks[start : start + self.max_batch])
            )
        return vecs


class CachedEmbedder:
    """LRU cache decorator (reference: cached_embedder.go)."""

    def __init__(self, inner: Embedder, capacity: int = 10_000):
        self.inner = inner
        self.capacity = capacity
        self.dims = inner.dims
        self._cache: "OrderedDict[str, List[float]]" = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        # expose the inner chunk path (uncached: chunk texts rarely repeat)
        if hasattr(inner, "embed_chunks"):
            self.embed_chunks = inner.embed_chunks

    def embed(self, text: str) -> List[float]:
        with self._lock:
            if text in self._cache:
                self._cache.move_to_end(text)
                self.hits += 1
                return list(self._cache[text])
        v = self.inner.embed(text)
        with self._lock:
            self.misses += 1
            self._cache[text] = list(v)
            while len(self._cache) > self.capacity:
                self._cache.popitem(last=False)
        return v

    def embed_batch(self, texts: Sequence[str]) -> List[List[float]]:
        with self._lock:
            # dedupe: repeated texts must cost one device call, not N
            missing = list(dict.fromkeys(t for t in texts if t not in self._cache))
        if missing:
            fresh = self.inner.embed_batch(missing)
            with self._lock:
                self.misses += len(missing)
                for t, v in zip(missing, fresh):
                    self._cache[t] = list(v)
                while len(self._cache) > self.capacity:
                    self._cache.popitem(last=False)
        out = []
        with self._lock:
            for t in texts:
                v = self._cache.get(t)
                if v is None:  # evicted between batches; recompute
                    v = self.inner.embed(t)
                else:
                    self._cache.move_to_end(t)
                out.append(list(v))
        return out
