"""Background embed queue: embeds un-embedded nodes and triggers indexing.

Reference: pkg/nornicdb/embed_queue.go — ``EmbedWorker`` (:21), batch
processing with retry (:498), debounced k-means/clustering trigger (:330),
periodic rescan (15 min), text assembly (:886 buildEmbeddingText).
Implements the MutationListener hook so the ListenableEngine feeds it
(reference wiring: db.go:1076-1080).
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Callable, List, Optional

from nornicdb_tpu.storage.types import Engine, MutationListener, Node

logger = logging.getLogger(__name__)

CHUNK_THRESHOLD_CHARS = 2000  # texts longer than this get chunk embeddings


def build_embedding_text(node: Node) -> str:
    """Reference: buildEmbeddingText (embed_queue.go:886)."""
    from nornicdb_tpu.search.service import extract_text

    return extract_text(node)


def embed_exempt(node: Node) -> bool:
    """System-owned nodes the queue must never embed: any label starting
    with ``_`` (Qdrant collections/points, internal meta). The Qdrant
    surface's vectors are client-authoritative (embedding-ownership
    rule, reference pkg/qdrantgrpc COMPAT.md:12-14)."""
    return any(lbl.startswith("_") for lbl in node.labels)


class EmbedQueue(MutationListener):
    def __init__(
        self,
        storage: Engine,
        embedder,
        on_embedded: Optional[Callable[[Node], None]] = None,
        batch_size: int = 16,
        max_retries: int = 3,
        rescan_interval_s: float = 900.0,
        cluster_debounce_s: float = 30.0,
        on_cluster: Optional[Callable[[], None]] = None,
    ):
        self.storage = storage
        self.embedder = embedder
        self.on_embedded = on_embedded
        self.batch_size = batch_size
        self.max_retries = max_retries
        self.rescan_interval_s = rescan_interval_s
        self.cluster_debounce_s = cluster_debounce_s
        self.on_cluster = on_cluster
        self._q: "queue.Queue[Optional[str]]" = queue.Queue()
        self._pending = set()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._worker: Optional[threading.Thread] = None
        self._rescanner: Optional[threading.Thread] = None
        self._cluster_timer: Optional[threading.Timer] = None
        self.embedded_count = 0
        self.failed_count = 0

    # -- MutationListener ------------------------------------------------

    def on_node_upsert(self, node: Node) -> None:
        if (
            node.embedding is None
            and not embed_exempt(node)
            and build_embedding_text(node)
        ):
            self.enqueue(node.id)

    def on_node_delete(self, node_id: str) -> None:
        with self._lock:
            self._pending.discard(node_id)

    # -- queue -----------------------------------------------------------

    def enqueue(self, node_id: str) -> None:
        with self._lock:
            if node_id in self._pending:
                return
            self._pending.add(node_id)
        self._q.put(node_id)

    def start(self) -> None:
        if self._worker is None:
            self._worker = threading.Thread(
                target=self._run, name="embed-queue", daemon=True
            )
            self._worker.start()
        if self._rescanner is None and self.rescan_interval_s > 0:
            self._rescanner = threading.Thread(
                target=self._rescan_loop, name="embed-rescan", daemon=True
            )
            self._rescanner.start()

    def stop(self) -> None:
        self._stop.set()
        self._q.put(None)
        if self._worker is not None:
            self._worker.join(timeout=10)
        if self._cluster_timer is not None:
            self._cluster_timer.cancel()

    def drain(self, timeout_s: float = 60.0) -> None:
        """Block until all currently-pending nodes are embedded."""
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            with self._lock:
                if not self._pending:
                    return
            time.sleep(0.02)

    # -- worker ----------------------------------------------------------

    def _run(self) -> None:
        # background maintenance lane (ISSUE 15): embedding catch-up
        # work seals behind interactive traffic in shared coalescers
        from nornicdb_tpu import admission as _adm

        _adm.lane_scope(_adm.LANE_BACKGROUND).__enter__()
        while not self._stop.is_set():
            batch: List[str] = []
            try:
                item = self._q.get(timeout=0.25)
            except queue.Empty:
                continue
            if item is None:
                break
            batch.append(item)
            while len(batch) < self.batch_size:
                try:
                    nxt = self._q.get_nowait()
                except queue.Empty:
                    break
                if nxt is None:
                    self._stop.set()
                    break
                batch.append(nxt)
            try:
                self._process_batch(batch)
            except Exception:
                logger.exception("embed batch failed")

    def _process_batch(self, node_ids: List[str]) -> None:
        nodes = []
        for nid in node_ids:
            try:
                node = self.storage.get_node(nid)
            except KeyError:
                with self._lock:
                    self._pending.discard(nid)
                continue
            if node.embedding is not None:
                with self._lock:
                    self._pending.discard(nid)
                continue
            nodes.append(node)
        if not nodes:
            return
        texts = [build_embedding_text(n) for n in nodes]
        vectors = self._embed_with_retry(texts)
        if vectors is None:
            self.failed_count += len(nodes)
            for n in nodes:
                with self._lock:
                    self._pending.discard(n.id)
            return
        for node, text, vec in zip(nodes, texts, vectors):
            # per-node isolation: one failing write must not wedge the rest
            # of the batch in _pending (they'd never re-enqueue)
            try:
                try:
                    fresh = self.storage.get_node(node.id)
                except KeyError:
                    continue
                fresh.embedding = list(vec)
                if len(text) > CHUNK_THRESHOLD_CHARS and hasattr(
                    self.embedder, "embed_chunks"
                ):
                    try:
                        fresh.chunk_embeddings = self.embedder.embed_chunks(text)
                    except Exception:
                        logger.exception("chunk embed failed for %s", node.id)
                try:
                    self.storage.update_node(fresh)
                except KeyError:
                    continue  # deleted concurrently
                self.embedded_count += 1
                if self.on_embedded is not None:
                    try:
                        self.on_embedded(fresh)
                    except Exception:
                        logger.exception("on_embedded callback failed")
            except Exception:
                logger.exception("embed write failed for %s", node.id)
                self.failed_count += 1
            finally:
                with self._lock:
                    self._pending.discard(node.id)
        self._schedule_clustering()

    def _embed_with_retry(self, texts: List[str]):
        """Reference: embedBatchWithRetry + llama crash recovery
        (local_gguf.go:202-254) — retries with backoff, fail-open."""
        delay = 0.1
        for attempt in range(self.max_retries):
            try:
                return self.embedder.embed_batch(texts)
            except Exception:
                logger.exception("embed attempt %d failed", attempt + 1)
                if attempt + 1 < self.max_retries:  # no sleep after the last try
                    time.sleep(delay)
                    delay *= 4
        return None

    # -- clustering debounce + rescan -------------------------------------

    def _schedule_clustering(self) -> None:
        """Debounced clustering trigger (reference:
        scheduleClusteringDebounced, embed_queue.go:330)."""
        if self.on_cluster is None:
            return
        with self._lock:
            if self._cluster_timer is not None:
                self._cluster_timer.cancel()
            self._cluster_timer = threading.Timer(
                self.cluster_debounce_s, self._fire_cluster
            )
            self._cluster_timer.daemon = True
            self._cluster_timer.start()

    def _fire_cluster(self) -> None:
        try:
            self.on_cluster()
        except Exception:
            logger.exception("clustering trigger failed")

    def _rescan_loop(self) -> None:
        """Periodic sweep for nodes that missed the event path
        (reference: 15-min rescan, embed_queue.go)."""
        while not self._stop.wait(self.rescan_interval_s):
            try:
                for node in self.storage.all_nodes():
                    if (
                        node.embedding is None
                        and not embed_exempt(node)
                        and build_embedding_text(node)
                    ):
                        self.enqueue(node.id)
            except Exception:
                logger.exception("rescan failed")
