"""Embedding pipeline: tokenizer, embedders, background embed queue.

Reference: pkg/embed (Embedder interface embed.go:71, providers Ollama/
OpenAI/local GGUF) + the embed queue worker (pkg/nornicdb/embed_queue.go).
The local path swaps llama.cpp-CUDA for the JAX encoder so ingest ->
embed -> index is TPU end-to-end (BASELINE.json north star).
"""

from nornicdb_tpu.embed.embedder import (  # noqa: F401
    CachedEmbedder,
    Embedder,
    HashEmbedder,
    JaxEncoderEmbedder,
)
from nornicdb_tpu.embed.http_providers import (  # noqa: F401
    EmbedHTTPError,
    OllamaEmbedder,
    OpenAIEmbedder,
    make_http_embedder,
)
from nornicdb_tpu.embed.tokenizer import HashTokenizer, chunk_tokens  # noqa: F401
from nornicdb_tpu.embed.queue import EmbedQueue  # noqa: F401
