"""Build the native HNSW connect-phase library with g++.

Invoked automatically (and cached on a source content hash) by
nornicdb_tpu.search.hnsw_native on first use; also runnable directly:
``python native/build_hnsw.py``.
"""

from __future__ import annotations

import importlib.util
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
# load the shared helper by path — native/ must never go on sys.path
# (it would shadow any top-level module named `build`)
_spec = importlib.util.spec_from_file_location(
    "nornicdb_tpu_native__buildlib", os.path.join(HERE, "_buildlib.py"))
_buildlib = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_buildlib)
build_cached, src_hash = _buildlib.build_cached, _buildlib.src_hash

SRC = os.path.join(HERE, "nornichnsw.cpp")
OUT = os.path.join(HERE, "libnornichnsw.so")
STAMP = OUT + ".srchash"


def _src_hash() -> str:
    return src_hash(SRC)


def build(force: bool = False) -> str:
    # -march=native is safe here: the stamp pins source hash AND host
    # CPU fingerprint, so a .so carried to a different machine is
    # rebuilt — or refused (Python fallback) when rebuild is impossible
    return build_cached(SRC, OUT, ["-O3", "-march=native", "-std=c++17"],
                        force=force)


if __name__ == "__main__":
    print(build(force="--force" in sys.argv))
