"""Build the native HNSW connect-phase library with g++.

Invoked automatically (and cached) by nornicdb_tpu.search.hnsw_native on
first use; also runnable directly: ``python native/build_hnsw.py``.
"""

from __future__ import annotations

import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
SRC = os.path.join(HERE, "nornichnsw.cpp")
OUT = os.path.join(HERE, "libnornichnsw.so")


def build(force: bool = False) -> str:
    if (
        not force
        and os.path.exists(OUT)
        and os.path.getmtime(OUT) >= os.path.getmtime(SRC)
    ):
        return OUT
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC",
        "-o", OUT + ".tmp", SRC,
    ]
    subprocess.run(cmd, check=True, capture_output=True)
    os.replace(OUT + ".tmp", OUT)
    return OUT


if __name__ == "__main__":
    print(build(force="--force" in sys.argv))
