// Native HNSW build kernels: wave layer-search + connect phase
// (diversity-select + link + back-link prune).
//
// The wave build (nornicdb_tpu/search/hnsw.py) vectorizes beam SEARCH
// across a whole wave with numpy einsums, which leaves the LINK phase —
// tens of thousands of small, sequential, data-dependent selections —
// as the remaining Python hot loop (~40% of build wall-clock, and the
// majority once the seeded bulk beam halves search work). This kernel
// executes the connect phase for one (level, wave) batch. Semantics
// mirror the Python reference implementation exactly:
//
// - _select_neighbors: keep a candidate (distance order) only if it is
//   closer to the query than to every already-kept neighbor; backfill
//   with the closest rejects if fewer than m survive; candidate list
//   capped at 4m.
// - _add_link: append a back-link while the row has slack; on overflow
//   re-select over (existing row + new link) by distance to the row
//   owner and rewrite the row.
//
// Equivalence with the Python path is pinned by
// tests/test_ann_stack.py::TestNativeConnect.
//
// Build: g++ -O3 -std=c++17 -shared -fPIC (native/build_hnsw.py, cached,
// invoked on demand by nornicdb_tpu/search/hnsw_native.py).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <limits>
#include <queue>
#include <vector>

namespace {

inline float dot(const float* a, const float* b, int64_t d) {
    // 16 independent accumulator lanes: strict-FP compilers can only
    // vectorize up to the manual unroll width (reassociation is not
    // allowed), so 4 lanes capped the loop at 128-bit SSE — 16 maps
    // onto two AVX2 registers (or one AVX-512) with -march=native
    float acc[16] = {0.f};
    int64_t i = 0;
    for (; i + 16 <= d; i += 16) {
        for (int k = 0; k < 16; ++k) acc[k] += a[i + k] * b[i + k];
    }
    float s = 0.f;
    for (int k = 0; k < 16; ++k) s += acc[k];
    for (; i < d; ++i) s += a[i] * b[i];
    return s;
}

// greedy diversity selection over candidates sorted by distance;
// returns number kept, writing kept slots into `out` (size >= m)
int64_t select_neighbors(const float* vectors, int64_t dims,
                         const int64_t* cslots, const float* cdists,
                         int64_t n_cand, int64_t m, int64_t* out) {
    n_cand = std::min(n_cand, 4 * m);
    if (n_cand <= m) {
        for (int64_t i = 0; i < n_cand; ++i) out[i] = cslots[i];
        return n_cand;
    }
    std::vector<char> taken(n_cand, 0);
    int64_t kept = 0;
    for (int64_t i = 0; i < n_cand && kept < m; ++i) {
        const float* vi = vectors + cslots[i] * dims;
        bool ok = true;
        for (int64_t k = 0; k < kept; ++k) {
            const float* vk = vectors + out[k] * dims;
            // closer to an already-kept neighbor than to the query
            if (cdists[i] >= 1.0f - dot(vi, vk, dims)) { ok = false; break; }
        }
        if (ok) {
            out[kept++] = cslots[i];
            taken[i] = 1;
        }
    }
    // backfill with the closest rejects (Python parity)
    for (int64_t i = 0; i < n_cand && kept < m; ++i) {
        if (!taken[i]) {
            out[kept++] = cslots[i];
            taken[i] = 1;
        }
    }
    return kept;
}

void set_row(int32_t* nbr, int32_t* cnt, int64_t width, int64_t row,
             const int64_t* slots, int64_t n) {
    n = std::min(n, width);
    int32_t* r = nbr + row * width;
    for (int64_t i = 0; i < n; ++i) r[i] = static_cast<int32_t>(slots[i]);
    for (int64_t i = n; i < width; ++i) r[i] = -1;
    cnt[row] = static_cast<int32_t>(n);
}

void add_link(const float* vectors, int64_t dims, int32_t* nbr,
              int32_t* cnt, int64_t width, int64_t level_cap,
              int64_t c, int64_t slot) {
    int32_t n = cnt[c];
    if (n < width) {
        nbr[c * width + n] = static_cast<int32_t>(slot);
        cnt[c] = n + 1;
        return;
    }
    // overflow: re-select over (existing row + new) by distance to c
    std::vector<std::pair<float, int64_t>> merged;
    merged.reserve(width + 1);
    const float* vc = vectors + c * dims;
    const int32_t* row = nbr + c * width;
    for (int64_t i = 0; i < width; ++i) {
        int64_t s = row[i];
        merged.emplace_back(1.0f - dot(vectors + s * dims, vc, dims), s);
    }
    merged.emplace_back(1.0f - dot(vectors + slot * dims, vc, dims), slot);
    std::sort(merged.begin(), merged.end());
    std::vector<int64_t> cs(merged.size());
    std::vector<float> cd(merged.size());
    for (size_t i = 0; i < merged.size(); ++i) {
        cd[i] = merged[i].first;
        cs[i] = merged[i].second;
    }
    std::vector<int64_t> out(level_cap);
    int64_t kept = select_neighbors(vectors, dims, cs.data(), cd.data(),
                                    static_cast<int64_t>(cs.size()),
                                    level_cap, out.data());
    set_row(nbr, cnt, width, c, out.data(), kept);
}

using DistSlot = std::pair<float, int64_t>;

// Classic HNSW layer search (searchLayer of the paper; the wave
// builder's per-query form). Entries seed both heaps; every candidate
// expansion is bounded by the current worst result once the result set
// is full. Results land in `out`, ascending by distance.
void search_layer_classic(const float* vectors, int64_t dims,
                          const float* q, const int32_t* nbr,
                          const int32_t* cnt, int64_t width,
                          const std::vector<DistSlot>& entries, int64_t ef,
                          std::vector<int32_t>& visited, int32_t genv,
                          std::vector<DistSlot>& out) {
    std::priority_queue<DistSlot> result;  // max-heap: top = worst kept
    std::priority_queue<DistSlot, std::vector<DistSlot>,
                        std::greater<DistSlot>> cands;  // min-heap
    for (const auto& e : entries) {
        visited[e.second] = genv;
        result.push(e);
        cands.push(e);
    }
    while (result.size() > static_cast<size_t>(ef)) result.pop();
    while (!cands.empty()) {
        DistSlot c = cands.top();
        if (result.size() >= static_cast<size_t>(ef) &&
            c.first > result.top().first)
            break;
        cands.pop();
        const int32_t* row = nbr + c.second * width;
        int32_t n = cnt[c.second];
        // the search is memory-latency-bound on the 1KB vector rows:
        // prefetch every unexpanded neighbor's row head before the
        // distance loop touches the first one
        for (int32_t i = 0; i < n; ++i) {
            __builtin_prefetch(vectors + row[i] * dims, 0, 1);
            __builtin_prefetch(visited.data() + row[i], 0, 1);
        }
        for (int32_t i = 0; i < n; ++i) {
            int64_t s = row[i];
            if (visited[s] == genv) continue;
            visited[s] = genv;
            float d = 1.0f - dot(q, vectors + s * dims, dims);
            if (result.size() < static_cast<size_t>(ef) ||
                d < result.top().first) {
                cands.emplace(d, s);
                result.emplace(d, s);
                if (result.size() > static_cast<size_t>(ef)) result.pop();
            }
        }
    }
    out.resize(result.size());
    for (int64_t i = static_cast<int64_t>(result.size()) - 1; i >= 0; --i) {
        out[i] = result.top();
        result.pop();
    }
}

}  // namespace

extern "C" {

// Wave layer-search for the bulk build: for each of B queries, greedy-
// descend from the global entry through levels above the query's level,
// then collect an ef-beam at every level from min(query_level, top)
// down to 0. Outputs land in [B, n_levels, ef] arrays (slot -1 / dist
// +inf padded), ascending by distance per (query, level) — exactly the
// per-level candidate lists hnsw.py's connect phase consumes.
//
// The graph traversed is the PRE-WAVE adjacency (wave slots exist in
// `vectors` but have no links yet), matching the Python wave builder.
void hnsw_wave_search(const float* vectors, int64_t dims,
                      const int32_t* const* nbrs,
                      const int32_t* const* cnts, const int64_t* widths,
                      int64_t n_levels, const float* queries, int64_t B,
                      const int64_t* query_levels, int64_t entry_slot,
                      int64_t ef, int64_t capacity, int64_t* out_slots,
                      float* out_dists) {
    const float INF = std::numeric_limits<float>::infinity();
    std::vector<int32_t> visited(capacity, 0);
    int32_t gen = 0;
    std::vector<DistSlot> beam, next;
    std::fill(out_slots, out_slots + B * n_levels * ef, int64_t{-1});
    std::fill(out_dists, out_dists + B * n_levels * ef, INF);
    for (int64_t j = 0; j < B; ++j) {
        const float* q = queries + j * dims;
        beam.assign(
            1, {1.0f - dot(q, vectors + entry_slot * dims, dims),
                entry_slot});
        int64_t top = std::min(query_levels[j], n_levels - 1);
        for (int64_t lv = n_levels - 1; lv > top; --lv) {
            ++gen;
            search_layer_classic(vectors, dims, q, nbrs[lv], cnts[lv],
                                 widths[lv], beam, 1, visited, gen, next);
            beam.swap(next);
        }
        for (int64_t lv = top; lv >= 0; --lv) {
            ++gen;
            search_layer_classic(vectors, dims, q, nbrs[lv], cnts[lv],
                                 widths[lv], beam, ef, visited, gen, next);
            beam.swap(next);
            int64_t* os = out_slots + (j * n_levels + lv) * ef;
            float* od = out_dists + (j * n_levels + lv) * ef;
            int64_t k = std::min<int64_t>(beam.size(), ef);
            for (int64_t i = 0; i < k; ++i) {
                od[i] = beam[i].first;
                os[i] = beam[i].second;
            }
        }
    }
}

// Connect a wave's nodes at ONE level. Candidates arrive flattened:
// node i's sorted-by-distance candidates are
// cand_slots[cand_off[i] : cand_off[i+1]] (+ parallel cand_dists).
// m_forward: forward-link selection size (the index's m at every
// level); level_cap: back-link prune cap (m0 at level 0, m above) —
// mirrors _link_from_cands(select m) + _add_link(prune to level cap).
void hnsw_connect(const float* vectors, int64_t dims, int32_t* nbr,
                  int32_t* cnt, int64_t width, int64_t m_forward,
                  int64_t level_cap,
                  const int64_t* wave_slots, const int64_t* cand_off,
                  const int64_t* cand_slots, const float* cand_dists,
                  int64_t n_wave) {
    std::vector<int64_t> out(std::max(m_forward, level_cap));
    for (int64_t i = 0; i < n_wave; ++i) {
        int64_t lo = cand_off[i], hi = cand_off[i + 1];
        int64_t kept = select_neighbors(
            vectors, dims, cand_slots + lo, cand_dists + lo, hi - lo,
            m_forward, out.data());
        int64_t slot = wave_slots[i];
        set_row(nbr, cnt, width, slot, out.data(), kept);
        for (int64_t k = 0; k < kept; ++k) {
            add_link(vectors, dims, nbr, cnt, width, level_cap, out[k],
                     slot);
        }
    }
}

}  // extern "C"
