"""Build the native nornickv shared library with g++.

Invoked automatically (and cached) by nornicdb_tpu.storage.disk on first
import; also runnable directly: ``python native/build.py``.
"""

from __future__ import annotations

import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
SRC = os.path.join(HERE, "nornickv.cpp")
OUT = os.path.join(HERE, "libnornickv.so")


def build(force: bool = False) -> str:
    """Compile if the .so is missing or older than the source. Returns the
    library path; raises on compiler failure."""
    if (
        not force
        and os.path.exists(OUT)
        and os.path.getmtime(OUT) >= os.path.getmtime(SRC)
    ):
        return OUT
    cmd = [
        "g++", "-O2", "-std=c++17", "-shared", "-fPIC",
        "-o", OUT + ".tmp", SRC,
    ]
    subprocess.run(cmd, check=True, capture_output=True)
    os.replace(OUT + ".tmp", OUT)
    return OUT


if __name__ == "__main__":
    print(build(force="--force" in sys.argv))
