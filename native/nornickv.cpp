// nornickv — log-structured persistent KV store (C++, no deps).
//
// TPU-native equivalent of the reference's BadgerEngine LSM store
// (reference: pkg/storage/badger.go:70 BadgerEngine, badger.go:436
// NewBadgerEngineWithOptions). Same durability contract: every acked
// mutation is on disk (append-only segment log), restart rebuilds the
// in-RAM key index by scanning segments, tombstones mark deletes, and
// compaction rewrites live records when dead bytes accumulate
// (Badger's value-log GC analog). CRC-framed records give torn-tail
// repair on crash (reference: wal_repair.go:25 repairWALTailIfNeeded).
//
// Exposed as a flat C ABI for ctypes (no pybind11 in this image).

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <dirent.h>
#include <fcntl.h>
#include <map>
#include <memory>
#include <mutex>
#include <shared_mutex>
#include <string>
#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>
#include <unordered_map>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0x4E4B5631;  // "NKV1"
constexpr uint8_t kOpPut = 1;
constexpr uint8_t kOpDel = 2;

// CRC32 (IEEE), small table-driven implementation.
uint32_t crc_table[256];
struct CrcInit {
  CrcInit() {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      crc_table[i] = c;
    }
  }
} crc_init;

uint32_t crc32(const uint8_t* p, size_t n, uint32_t crc = 0) {
  crc = ~crc;
  for (size_t i = 0; i < n; i++) crc = crc_table[(crc ^ p[i]) & 0xFF] ^ (crc >> 8);
  return ~crc;
}

struct Loc {
  uint32_t segment;
  uint64_t offset;   // offset of record start
  uint32_t vlen;
  uint64_t voffset;  // offset of value bytes within segment
};

std::string seg_name(const std::string& dir, uint32_t id) {
  char buf[32];
  snprintf(buf, sizeof buf, "/kv-%06u.log", id);
  return dir + buf;
}

struct Store {
  std::string dir;
  bool sync_every_write = false;
  uint64_t max_segment_bytes = 64ull << 20;
  int active_fd = -1;
  uint32_t active_seg = 0;
  uint64_t active_off = 0;
  std::map<std::string, Loc> index;  // ordered: prefix scans are ranges
  uint64_t live_bytes = 0, dead_bytes = 0;
  int repaired = 0;  // torn-tail truncations performed during open
  std::shared_mutex mu;

  ~Store() {
    if (active_fd >= 0) ::close(active_fd);
  }
};

bool read_exact(int fd, void* buf, size_t n) {
  size_t got = 0;
  while (got < n) {
    ssize_t r = ::read(fd, (char*)buf + got, n - got);
    if (r <= 0) return false;
    got += (size_t)r;
  }
  return true;
}

bool write_all(int fd, const void* buf, size_t n) {
  size_t put = 0;
  while (put < n) {
    ssize_t r = ::write(fd, (const char*)buf + put, n - put);
    if (r < 0) return false;
    put += (size_t)r;
  }
  return true;
}

// Scan one segment, updating the index. Returns false on unrecoverable IO
// error. A corrupt/truncated record truncates the file there (torn tail).
bool scan_segment(Store* s, uint32_t seg_id) {
  std::string path = seg_name(s->dir, seg_id);
  int fd = ::open(path.c_str(), O_RDWR);
  if (fd < 0) return false;
  uint64_t off = 0;
  for (;;) {
    uint8_t hdr[13];  // magic(4) op(1) klen(4) vlen(4)
    if (!read_exact(fd, hdr, sizeof hdr)) break;  // clean EOF or short tail
    uint32_t magic, klen, vlen;
    memcpy(&magic, hdr, 4);
    uint8_t op = hdr[4];
    memcpy(&klen, hdr + 5, 4);
    memcpy(&vlen, hdr + 9, 4);
    if (magic != kMagic || (op != kOpPut && op != kOpDel) ||
        klen > (64u << 20) || vlen > (1u << 30)) {
      // corrupt header: truncate here
      if (::ftruncate(fd, (off_t)off) == 0) s->repaired++;
      break;
    }
    std::vector<uint8_t> body(klen + vlen + 4);
    if (!read_exact(fd, body.data(), body.size())) {
      if (::ftruncate(fd, (off_t)off) == 0) s->repaired++;
      break;
    }
    uint32_t want;
    memcpy(&want, body.data() + klen + vlen, 4);
    uint32_t got = crc32(hdr + 4, 9);
    got = crc32(body.data(), klen + vlen, got);
    if (want != got) {
      if (::ftruncate(fd, (off_t)off) == 0) s->repaired++;
      break;
    }
    std::string key((const char*)body.data(), klen);
    uint64_t rec_len = sizeof hdr + body.size();
    auto it = s->index.find(key);
    if (it != s->index.end()) {
      // the superseded record stops being live regardless of the new op
      s->dead_bytes += it->second.vlen + (uint64_t)it->first.size() + 17;
      s->live_bytes -= it->second.vlen + key.size() + 17;
      s->index.erase(it);
    }
    if (op == kOpPut) {
      Loc loc{seg_id, off, vlen, off + sizeof hdr + klen};
      s->index[key] = loc;
      s->live_bytes += rec_len;
    } else {
      s->dead_bytes += rec_len;  // tombstone itself is dead weight
    }
    off += rec_len;
  }
  ::close(fd);
  if (seg_id == s->active_seg) s->active_off = off;
  return true;
}

int open_active(Store* s) {
  std::string path = seg_name(s->dir, s->active_seg);
  s->active_fd = ::open(path.c_str(), O_CREAT | O_RDWR | O_APPEND, 0644);
  return s->active_fd < 0 ? -1 : 0;
}

int roll_segment_locked(Store* s) {
  if (s->active_fd >= 0) {
    ::fsync(s->active_fd);
    ::close(s->active_fd);
  }
  s->active_seg++;
  s->active_off = 0;
  return open_active(s);
}

int append_locked(Store* s, uint8_t op, const char* k, uint32_t klen,
                  const char* v, uint32_t vlen) {
  if (s->active_off >= s->max_segment_bytes)
    if (roll_segment_locked(s) != 0) return -1;
  uint8_t hdr[13];
  memcpy(hdr, &kMagic, 4);
  hdr[4] = op;
  memcpy(hdr + 5, &klen, 4);
  memcpy(hdr + 9, &vlen, 4);
  uint32_t crc = crc32(hdr + 4, 9);
  crc = crc32((const uint8_t*)k, klen, crc);
  if (vlen) crc = crc32((const uint8_t*)v, vlen, crc);
  std::vector<uint8_t> rec(sizeof hdr + klen + vlen + 4);
  memcpy(rec.data(), hdr, sizeof hdr);
  memcpy(rec.data() + sizeof hdr, k, klen);
  if (vlen) memcpy(rec.data() + sizeof hdr + klen, v, vlen);
  memcpy(rec.data() + sizeof hdr + klen + vlen, &crc, 4);
  if (!write_all(s->active_fd, rec.data(), rec.size())) {
    // a partial write (ENOSPC etc.) must not desync active_off from real
    // EOF: roll the file back to the last good record boundary
    ::ftruncate(s->active_fd, (off_t)s->active_off);
    return -1;
  }
  uint64_t off = s->active_off;
  s->active_off += rec.size();
  if (s->sync_every_write) ::fsync(s->active_fd);

  std::string key(k, klen);
  auto it = s->index.find(key);
  if (it != s->index.end()) {
    s->dead_bytes += it->second.vlen + key.size() + 17;
    s->live_bytes -= it->second.vlen + key.size() + 17;
    s->index.erase(it);
  }
  if (op == kOpPut) {
    s->index[key] = Loc{s->active_seg, off, vlen, off + sizeof hdr + klen};
    s->live_bytes += rec.size();
  } else {
    s->dead_bytes += rec.size();
  }
  return 0;
}

int read_value(Store* s, const Loc& loc, char** val, int* vlen) {
  *val = (char*)malloc(loc.vlen ? loc.vlen : 1);
  if (!*val) return -1;
  *vlen = (int)loc.vlen;
  if (loc.vlen == 0) return 0;
  if (loc.segment == s->active_seg && s->active_fd >= 0) {
    ssize_t r = ::pread(s->active_fd, *val, loc.vlen, (off_t)loc.voffset);
    if (r == (ssize_t)loc.vlen) return 0;
  }
  std::string path = seg_name(s->dir, loc.segment);
  int fd = ::open(path.c_str(), O_RDONLY);
  if (fd < 0) { free(*val); return -1; }
  ssize_t r = ::pread(fd, *val, loc.vlen, (off_t)loc.voffset);
  ::close(fd);
  if (r != (ssize_t)loc.vlen) { free(*val); return -1; }
  return 0;
}

struct ScanIter {
  Store* store;
  std::vector<std::string> keys;
  size_t pos = 0;
};

}  // namespace

extern "C" {

void* nkv_open(const char* dir, int sync_every_write, long max_segment_bytes) {
  auto s = std::make_unique<Store>();
  s->dir = dir;
  s->sync_every_write = sync_every_write != 0;
  if (max_segment_bytes > 0) s->max_segment_bytes = (uint64_t)max_segment_bytes;
  ::mkdir(dir, 0755);
  // discover segments
  std::vector<uint32_t> segs;
  if (DIR* d = ::opendir(dir)) {
    while (dirent* e = ::readdir(d)) {
      unsigned id;
      if (sscanf(e->d_name, "kv-%06u.log", &id) == 1) segs.push_back(id);
    }
    ::closedir(d);
  }
  std::sort(segs.begin(), segs.end());
  s->active_seg = segs.empty() ? 0 : segs.back();
  for (uint32_t id : segs)
    if (!scan_segment(s.get(), id)) return nullptr;
  if (open_active(s.get()) != 0) return nullptr;
  return s.release();
}

int nkv_put(void* h, const char* k, int klen, const char* v, int vlen) {
  auto* s = (Store*)h;
  std::unique_lock lock(s->mu);
  return append_locked(s, kOpPut, k, (uint32_t)klen, v, (uint32_t)vlen);
}

int nkv_get(void* h, const char* k, int klen, char** val, int* vlen) {
  auto* s = (Store*)h;
  std::shared_lock lock(s->mu);
  auto it = s->index.find(std::string(k, klen));
  if (it == s->index.end()) return 1;
  return read_value(s, it->second, val, vlen) == 0 ? 0 : -1;
}

int nkv_has(void* h, const char* k, int klen) {
  auto* s = (Store*)h;
  std::shared_lock lock(s->mu);
  return s->index.count(std::string(k, klen)) ? 1 : 0;
}

int nkv_delete(void* h, const char* k, int klen) {
  auto* s = (Store*)h;
  std::unique_lock lock(s->mu);
  if (!s->index.count(std::string(k, klen))) return 1;
  return append_locked(s, kOpDel, k, (uint32_t)klen, nullptr, 0);
}

long nkv_count(void* h) {
  auto* s = (Store*)h;
  std::shared_lock lock(s->mu);
  return (long)s->index.size();
}

long nkv_count_prefix(void* h, const char* p, int plen) {
  auto* s = (Store*)h;
  std::shared_lock lock(s->mu);
  std::string prefix(p, plen);
  long n = 0;
  for (auto it = s->index.lower_bound(prefix);
       it != s->index.end() && it->first.compare(0, prefix.size(), prefix) == 0;
       ++it)
    n++;
  return n;
}

long nkv_live_bytes(void* h) {
  auto* s = (Store*)h;
  std::shared_lock lock(s->mu);
  return (long)s->live_bytes;
}

long nkv_dead_bytes(void* h) {
  auto* s = (Store*)h;
  std::shared_lock lock(s->mu);
  return (long)s->dead_bytes;
}

int nkv_repaired(void* h) {
  auto* s = (Store*)h;
  return s->repaired;
}

int nkv_sync(void* h) {
  auto* s = (Store*)h;
  std::unique_lock lock(s->mu);
  return s->active_fd >= 0 ? ::fsync(s->active_fd) : 0;
}

// Rewrite all live records into fresh segments, drop old ones.
int nkv_compact(void* h) {
  auto* s = (Store*)h;
  std::unique_lock lock(s->mu);
  uint32_t first_new = s->active_seg + 1;
  std::vector<uint32_t> old_segs;
  for (uint32_t id = 0; id <= s->active_seg; id++) {
    struct stat st;
    if (::stat(seg_name(s->dir, id).c_str(), &st) == 0) old_segs.push_back(id);
  }
  // snapshot live entries (key -> value bytes)
  std::vector<std::pair<std::string, std::string>> live;
  live.reserve(s->index.size());
  for (auto& [key, loc] : s->index) {
    char* v = nullptr;
    int vlen = 0;
    if (read_value(s, loc, &v, &vlen) != 0) return -1;
    live.emplace_back(key, std::string(v, (size_t)vlen));
    free(v);
  }
  if (s->active_fd >= 0) { ::fsync(s->active_fd); ::close(s->active_fd); }
  s->active_seg = first_new;
  s->active_off = 0;
  s->index.clear();
  s->live_bytes = s->dead_bytes = 0;
  if (open_active(s) != 0) return -1;
  for (auto& [key, val] : live)
    if (append_locked(s, kOpPut, key.data(), (uint32_t)key.size(), val.data(),
                      (uint32_t)val.size()) != 0)
      return -1;
  ::fsync(s->active_fd);
  for (uint32_t id : old_segs) ::unlink(seg_name(s->dir, id).c_str());
  return 0;
}

void* nkv_scan(void* h, const char* p, int plen) {
  auto* s = (Store*)h;
  auto* it = new ScanIter();
  it->store = s;
  std::shared_lock lock(s->mu);
  std::string prefix(p, plen);
  for (auto i = s->index.lower_bound(prefix);
       i != s->index.end() && i->first.compare(0, prefix.size(), prefix) == 0;
       ++i)
    it->keys.push_back(i->first);
  return it;
}

int nkv_scan_next(void* iter, char** k, int* klen, char** v, int* vlen) {
  auto* it = (ScanIter*)iter;
  Store* s = it->store;
  while (it->pos < it->keys.size()) {
    const std::string& key = it->keys[it->pos++];
    std::shared_lock lock(s->mu);
    auto found = s->index.find(key);
    if (found == s->index.end()) continue;  // deleted since snapshot
    *k = (char*)malloc(key.size() ? key.size() : 1);
    memcpy(*k, key.data(), key.size());
    *klen = (int)key.size();
    if (read_value(s, found->second, v, vlen) != 0) { free(*k); return -1; }
    return 0;
  }
  return 1;  // exhausted
}

void nkv_scan_free(void* iter) { delete (ScanIter*)iter; }

void nkv_free(char* p) { free(p); }

void nkv_close(void* h) {
  auto* s = (Store*)h;
  {
    std::unique_lock lock(s->mu);
    if (s->active_fd >= 0) { ::fsync(s->active_fd); ::close(s->active_fd); s->active_fd = -1; }
  }
  delete s;
}

}  // extern "C"
