"""Shared content-hash-cached g++ build for the native libraries.

The cache is keyed on a sha256 of the source, not mtimes: a fresh clone
has arbitrary checkout mtimes, and a committed .so that no longer
matches its .cpp must never be silently loaded (ADVICE r4). Degradation
order when a rebuild is impossible: existing .so with a warning (still
faster and behaviorally pinned by the parity tests) rather than an
exception that would silently drop callers to their slow Python paths.
"""

from __future__ import annotations

import hashlib
import logging
import os
import subprocess

log = logging.getLogger("nornicdb_tpu.native")


def src_hash(src: str) -> str:
    with open(src, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()


def host_tag() -> str:
    """Fingerprint of the CPU the library was compiled on. -march=native
    output must never execute on a CPU with a different ISA extension
    set (SIGILL, not a catchable error), so the stamp pins the host and
    a mismatch forces a rebuild — or a clean refusal when rebuild is
    impossible, which drops callers to their Python fallbacks."""
    import platform

    tag = platform.machine()
    try:
        with open("/proc/cpuinfo", encoding="utf-8") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    tag += ":" + line.split(":", 1)[1]
                    break
    except OSError:
        pass
    return hashlib.sha256(tag.encode()).hexdigest()[:16]


def _stamp_fields(stamp: str) -> list:
    try:
        with open(stamp, encoding="utf-8") as f:
            return f.read().split()
    except OSError:
        return []


def _stamp_ok(stamp: str, want, host: str) -> bool:
    fields = _stamp_fields(stamp)
    # legacy single-field stamps (no host tag) don't vouch for ISA
    return (len(fields) >= 2 and fields[1] == host
            and (want is None or fields[0] == want))


def build_cached(src: str, out: str, flags: list[str],
                 force: bool = False) -> str:
    """Compile ``src`` to ``out`` unless a stamp file proves the existing
    ``out`` was built from byte-identical source ON THIS CPU. Returns
    the library path; raises only when no usable library can be
    produced at all."""
    stamp = out + ".srchash"
    host = host_tag()
    if not os.path.exists(src):
        # deployment without sources: the prebuilt .so is all there is —
        # but only if it was provably compiled on this CPU
        if os.path.exists(out) and _stamp_ok(stamp, None, host):
            return out
        raise FileNotFoundError(
            f"{src} missing and no ISA-matched prebuilt {out}")
    want = src_hash(src)
    if not force and os.path.exists(out) and _stamp_ok(stamp, want, host):
        return out
    # per-process temp names: concurrent first-use builds (e.g. two
    # services starting on a fresh clone) must not interleave writes to
    # one shared .tmp and publish a truncated library
    tmp_out = f"{out}.tmp.{os.getpid()}"
    tmp_stamp = f"{stamp}.tmp.{os.getpid()}"
    cmd = ["g++", *flags, "-shared", "-fPIC", "-o", tmp_out, src]
    try:
        subprocess.run(cmd, check=True, capture_output=True)
    except (OSError, subprocess.CalledProcessError) as exc:
        # stale content is tolerable (parity tests pin behavior); a
        # foreign-ISA binary is not — executing it can SIGILL
        if os.path.exists(out) and _stamp_ok(stamp, None, host):
            log.warning(
                "cannot rebuild %s (%s); loading the existing library, "
                "which may not match %s", out, exc, src,
            )
            return out
        raise
    os.replace(tmp_out, out)
    with open(tmp_stamp, "w", encoding="utf-8") as f:
        f.write(want + "\n" + host + "\n")
    os.replace(tmp_stamp, stamp)
    return out
