"""Shared content-hash-cached g++ build for the native libraries.

The cache is keyed on a sha256 of the source, not mtimes: a fresh clone
has arbitrary checkout mtimes, and a committed .so that no longer
matches its .cpp must never be silently loaded (ADVICE r4). Degradation
order when a rebuild is impossible: existing .so with a warning (still
faster and behaviorally pinned by the parity tests) rather than an
exception that would silently drop callers to their slow Python paths.
"""

from __future__ import annotations

import hashlib
import logging
import os
import subprocess

log = logging.getLogger("nornicdb_tpu.native")


def src_hash(src: str) -> str:
    with open(src, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()


def build_cached(src: str, out: str, flags: list[str],
                 force: bool = False) -> str:
    """Compile ``src`` to ``out`` unless a stamp file proves the existing
    ``out`` was built from byte-identical source. Returns the library
    path; raises only when no usable library can be produced at all."""
    stamp = out + ".srchash"
    if not os.path.exists(src):
        # deployment without sources: the prebuilt .so is all there is
        if os.path.exists(out):
            return out
        raise FileNotFoundError(src)
    want = src_hash(src)
    if not force and os.path.exists(out) and os.path.exists(stamp):
        try:
            with open(stamp, encoding="utf-8") as f:
                if f.read().strip() == want:
                    return out
        except OSError:
            pass
    # per-process temp names: concurrent first-use builds (e.g. two
    # services starting on a fresh clone) must not interleave writes to
    # one shared .tmp and publish a truncated library
    tmp_out = f"{out}.tmp.{os.getpid()}"
    tmp_stamp = f"{stamp}.tmp.{os.getpid()}"
    cmd = ["g++", *flags, "-shared", "-fPIC", "-o", tmp_out, src]
    try:
        subprocess.run(cmd, check=True, capture_output=True)
    except (OSError, subprocess.CalledProcessError) as exc:
        if os.path.exists(out):
            log.warning(
                "cannot rebuild %s (%s); loading the existing library, "
                "which may not match %s", out, exc, src,
            )
            return out
        raise
    os.replace(tmp_out, out)
    with open(tmp_stamp, "w", encoding="utf-8") as f:
        f.write(want + "\n")
    os.replace(tmp_stamp, stamp)
    return out
